"""Label-map lookup + fetch tool + top-k printing (utils/preds.py)."""
import importlib.util
import os
import sys
from pathlib import Path

import numpy as np
import pytest

from video_features_tpu.utils.preds import (
    load_label_map, show_predictions_on_dataset, softmax,
)

REPO_ROOT = Path(__file__).parent.parent


def _run_fetch_tool(out_dir, checkout):
    spec = importlib.util.spec_from_file_location(
        'fetch_label_maps', REPO_ROOT / 'tools' / 'fetch_label_maps.py')
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    argv = sys.argv
    sys.argv = ['fetch_label_maps', '--out', str(out_dir),
                '--from-checkout', str(checkout)]
    try:
        return mod.main()
    finally:
        sys.argv = argv


def test_fetch_tool_and_env_lookup(tmp_path, reference_repo, monkeypatch):
    rc = _run_fetch_tool(tmp_path, reference_repo)
    assert rc == 0
    assert (tmp_path / 'K400_label_map.txt').exists()

    monkeypatch.setenv('VFT_LABEL_MAP_DIR', str(tmp_path))
    classes = load_label_map('kinetics')
    assert classes is not None and len(classes) == 400


def test_load_label_map_unknown_dataset():
    assert load_label_map('nonsense') is None


def test_bundled_label_maps_resolve_air_gapped(monkeypatch):
    """The three maps ship as package data: with no env var and no
    reference checkout, class names must still resolve (air-gapped host)."""
    monkeypatch.delenv('VFT_LABEL_MAP_DIR', raising=False)
    for dataset, n in (('kinetics', 400), ('imagenet1k', 1000),
                      ('imagenet21k', 21843)):
        classes = load_label_map(dataset)
        assert classes is not None and len(classes) == n, dataset


def test_softmax_rows_sum_to_one():
    x = np.random.RandomState(0).randn(3, 10)
    p = softmax(x)
    np.testing.assert_allclose(p.sum(-1), np.ones(3), atol=1e-6)


def test_show_predictions_falls_back_to_indices(capsys, monkeypatch):
    # point the search path somewhere empty: indices must print, not raise
    monkeypatch.setenv('VFT_LABEL_MAP_DIR', '/nonexistent')
    logits = np.random.RandomState(0).randn(2, 40).astype(np.float32)
    show_predictions_on_dataset(logits, 'nonsense', k=3)
    out = capsys.readouterr().out
    assert 'class_' in out and out.count('Logits') == 2


def test_show_predictions_with_custom_class_list(capsys):
    logits = np.array([[0.1, 5.0, -1.0]], np.float32)
    show_predictions_on_dataset(logits, ['cat', 'dog', 'fish'], k=2)
    out = capsys.readouterr().out
    assert 'dog' in out
