"""The driver contract: `python bench.py` prints exactly ONE JSON line.

The driver records this line as BENCH_r{N}.json at the end of every round;
a malformed line or a second print loses the round's benchmark. Runs the
real script on CPU at a tiny smoke geometry.
"""
import json
import os
import subprocess
import sys
from pathlib import Path

import pytest

pytestmark = pytest.mark.slow

REPO = Path(__file__).resolve().parents[1]


def _run_bench(extra_env):
    env = dict(os.environ, BENCH_PLATFORM='cpu', BENCH_SIZE='48',
               BENCH_ITERS='1', JAX_PLATFORMS='cpu', **extra_env)
    out = subprocess.run(
        [sys.executable, str(REPO / 'bench.py')], env=env, cwd=str(REPO),
        capture_output=True, text=True, timeout=900)
    assert out.returncode == 0, out.stderr[-2000:]
    lines = [ln for ln in out.stdout.splitlines() if ln.strip()]
    assert len(lines) == 1, f'expected ONE line, got: {lines}'
    rec = json.loads(lines[0])
    assert set(rec) == {'metric', 'value', 'unit', 'vs_baseline', 'rungs',
                        'stage_reports'}
    assert rec['unit'] == 'clips/sec/chip'
    assert rec['value'] > 0
    assert rec['rungs']
    return rec


def test_bench_prints_one_json_line():
    rec = _run_bench({})
    # the metric name must stamp the precision that produced the number
    assert 'mixed' in rec['metric'] or os.environ.get('BENCH_PRECISION')


def test_bench_mode_both_keeps_contract():
    """The accelerator default (BENCH_MODE=both) walks the e2e path, whose
    extractor runs allow_random_weights and a real decode loop — all of
    whose warnings/diagnostics must land on stderr, never stdout
    (advisor round-2 medium finding)."""
    rec = _run_bench({'BENCH_MODE': 'both', 'BENCH_E2E_RUNS': '1',
                      'BENCH_VIDEO': 'synthetic', 'BENCH_E2E_SECONDS': '1'})
    # both rungs recorded (or an explicit e2e_error key — never a crash)
    assert any(k.startswith('ingraph_') for k in rec['rungs'])
    assert any(k.startswith('e2e') for k in rec['rungs'])
    # instrumented rungs embed their per-stage Tracer report: the record
    # explains its own number (tools/bench_diff.py reads these)
    if not any(k.endswith('e2e_error') for k in rec['rungs']):
        e2e_reports = [v for k, v in rec['stage_reports'].items()
                       if k.startswith('e2e')]
        assert e2e_reports and all('count' in s and 'total_s' in s
                                   for rep in e2e_reports
                                   for s in rep.values())


def test_bench_worklist_async_rung_emits_keys():
    """BENCH_WORKLIST=1 runs the corpus ladder: the per-video loop, the
    packed loop pinned synchronous (inflight=1 decode_workers=1), the
    async deferred-D2H loop (inflight=2), and the decode-farm loop
    (decode_workers>1 — multi-process decode over SHM rings). The record
    must carry all four clips/sec rungs, the metadata naming which loop
    each rung ran, and stage reports in which the async rung shows the
    d2h stage split out of model."""
    rec = _run_bench({'BENCH_MODE': 'both', 'BENCH_E2E_RUNS': '1',
                      'BENCH_VIDEO': 'synthetic', 'BENCH_E2E_SECONDS': '1',
                      'BENCH_WORKLIST': '1', 'BENCH_SERVE': '0',
                      'BENCH_CACHE': '0',
                      # pin the mesh rung's width: the conftest's forced
                      # 8 host devices would auto-detect to an 8-wide
                      # mesh, pointlessly heavy for a contract smoke
                      'BENCH_MESH_DEVICES': '2',
                      # rung KEYS are family-independent; resnet keeps
                      # the CPU smoke off the RAFT-on-CPU cost cliff
                      'BENCH_WORKLIST_FEATURE': 'resnet'})
    rungs = rec['rungs']
    for err in ('worklist_error', 'worklist_packed_error',
                'worklist_async_error', 'worklist_farm_error',
                'worklist_mesh_error'):
        assert err not in rungs, rungs.get(err)
    assert any(k.startswith('worklist_clips_per_sec') for k in rungs)
    assert any(k.startswith('worklist_packed_clips_per_sec')
               for k in rungs)
    assert any(k.startswith('worklist_async_clips_per_sec') for k in rungs)
    # the decode-farm rung (farm/): same async loop, decode in worker
    # PROCESSES over shared-memory rings
    assert any(k.startswith('worklist_farm_clips_per_sec') for k in rungs)
    # the mesh rung (parallel/mesh.py): the async loop's batches planned
    # at capacity × ndev and sharded over the data axis
    assert any(k.startswith('worklist_mesh_clips_per_sec') for k in rungs)
    # rung metadata: which device loop / input side produced each number
    assert rungs['worklist_packed_inflight'] == 1
    assert rungs['worklist_async_inflight'] == 2
    assert rungs['worklist_farm_decode_workers'] >= 2
    assert rungs['worklist_mesh_devices'] == 2
    # the farm rung's stage report carries the workers' own decode spans
    farm_rep = next(v for k, v in rec['stage_reports'].items()
                    if k.startswith('worklist_farm'))
    assert 'decode' in farm_rep and 'model' in farm_rep
    # the async rung's stage report splits d2h out of model; the shares
    # are distinct stages, not one laundered span
    async_rep = next(v for k, v in rec['stage_reports'].items()
                     if k.startswith('worklist_async'))
    assert 'model' in async_rep and 'd2h' in async_rep
    assert async_rep['d2h']['count'] == async_rep['model']['count']
    # the synchronous rung records them too (inflight=1 still fetches
    # through the same d2h sync point, just immediately)
    packed_rep = next(v for k, v in rec['stage_reports'].items()
                     if k.startswith('worklist_packed'))
    assert 'd2h' in packed_rep


def test_bench_bf16_rungs_emit_keys():
    """BENCH_BF16=1 drives the bf16 fast-lane rungs: the in-graph
    framewise pair (fp32 vs bf16 on the SAME resnet step) and the packed
    worklist pair — every speedup recorded WITH its measured error, and
    the error under the family's pinned parity bound. fp32 rung keys are
    untouched (default path byte-identical)."""
    from video_features_tpu.ops.precision import BF16_REL_L2_BOUNDS
    rec = _run_bench({'BENCH_MODE': 'both', 'BENCH_E2E_RUNS': '1',
                      'BENCH_VIDEO': 'synthetic', 'BENCH_E2E_SECONDS': '1',
                      'BENCH_WORKLIST': '1', 'BENCH_SERVE': '0',
                      'BENCH_CACHE': '0', 'BENCH_BF16': '1',
                      'BENCH_BF16_SERVE': '0',
                      'BENCH_WORKLIST_FEATURE': 'resnet'})
    rungs = rec['rungs']
    for err in ('bf16_ingraph_error', 'worklist_bf16_error'):
        assert err not in rungs, rungs.get(err)
    # in-graph framewise pair: speedup + error always recorded together
    assert rungs['resnet_ingraph_bf16_frames_per_sec'] > 0
    assert rungs['resnet_ingraph_bf16_fp32_frames_per_sec'] > 0
    assert rungs['resnet_ingraph_bf16_speedup'] > 0
    assert rungs['resnet_ingraph_bf16_max_abs_error'] > 0
    assert 0 < rungs['resnet_ingraph_bf16_rel_l2_error'] \
        <= BF16_REL_L2_BOUNDS['resnet']
    # packed worklist pair: real files, fp32 sibling rung beside it
    assert rungs['worklist_packed_bf16_clips_per_sec'] > 0
    assert rungs['worklist_packed_bf16_fp32_clips_per_sec'] > 0
    assert rungs['worklist_packed_bf16_speedup'] > 0
    assert rungs['worklist_packed_bf16_max_abs_error'] > 0
    assert 0 < rungs['worklist_packed_bf16_rel_l2_error'] \
        <= BF16_REL_L2_BOUNDS['resnet']
    assert rungs['worklist_bf16_compute_dtype'] == 'bfloat16'
    # fp32 rungs keep their historical keys (the default path's numbers
    # never get relabelled by the lane's arrival)
    assert any(k.startswith('worklist_packed_clips_per_sec')
               for k in rungs)


def test_bench_int8_rungs_emit_keys():
    """BENCH_INT8=1 drives the int8 weight-lane rungs: the in-graph
    framewise pair (fp32 vs int8 on the SAME resnet step — quantized
    params, in-graph dequant, fp32 activations) and the packed worklist
    pair — every speedup recorded WITH its measured error, and the error
    under the family's pinned ``INT8_REL_L2_BOUNDS`` entry. fp32 rung
    keys are untouched."""
    from video_features_tpu.ops.precision import INT8_REL_L2_BOUNDS
    rec = _run_bench({'BENCH_MODE': 'both', 'BENCH_E2E_RUNS': '1',
                      'BENCH_VIDEO': 'synthetic', 'BENCH_E2E_SECONDS': '1',
                      'BENCH_WORKLIST': '1', 'BENCH_SERVE': '0',
                      'BENCH_CACHE': '0', 'BENCH_INT8': '1',
                      'BENCH_INT8_SERVE': '0', 'BENCH_BF16': '0',
                      'BENCH_WORKLIST_FEATURE': 'resnet'})
    rungs = rec['rungs']
    for err in ('int8_ingraph_error', 'worklist_int8_error'):
        assert err not in rungs, rungs.get(err)
    # in-graph framewise pair: speedup + error always recorded together
    assert rungs['resnet_ingraph_int8_frames_per_sec'] > 0
    assert rungs['resnet_ingraph_int8_fp32_frames_per_sec'] > 0
    assert rungs['resnet_ingraph_int8_speedup'] > 0
    assert rungs['resnet_ingraph_int8_max_abs_error'] > 0
    assert 0 < rungs['resnet_ingraph_int8_rel_l2_error'] \
        <= INT8_REL_L2_BOUNDS['resnet']
    # packed worklist pair: real files, fp32 sibling rung beside it
    assert rungs['worklist_packed_int8_clips_per_sec'] > 0
    assert rungs['worklist_packed_int8_fp32_clips_per_sec'] > 0
    assert rungs['worklist_packed_int8_speedup'] > 0
    assert rungs['worklist_packed_int8_max_abs_error'] > 0
    assert 0 < rungs['worklist_packed_int8_rel_l2_error'] \
        <= INT8_REL_L2_BOUNDS['resnet']
    assert rungs['worklist_int8_compute_dtype'] == 'int8'
    # fp32 rungs keep their historical keys
    assert any(k.startswith('worklist_packed_clips_per_sec')
               for k in rungs)


def test_bench_fused_rung_emits_keys():
    """BENCH_FUSED=1 drives the fused multi-family rung: one
    ``features=[...]`` pass (decode + sha256 once per video, N families
    out) vs N sequential per-family passes, byte-parity-checked before
    any rate is recorded. The hash amortization is a deterministic
    counter ratio — exactly N for N families — while the wall-clock
    speedup and decode amortization are timing-based and only asserted
    present; the family set rides as config metadata."""
    rec = _run_bench({'BENCH_MODE': 'both', 'BENCH_E2E_RUNS': '1',
                      'BENCH_VIDEO': 'synthetic', 'BENCH_E2E_SECONDS': '1',
                      'BENCH_WORKLIST': '1', 'BENCH_SERVE': '0',
                      'BENCH_CACHE': '0', 'BENCH_FUSED': '1',
                      'BENCH_MESH_DEVICES': '2',
                      'BENCH_WORKLIST_FEATURE': 'resnet',
                      # two cheap framewise families keep the CPU smoke
                      # off a third model transplant; the rung KEYS are
                      # family-set-independent
                      'BENCH_FUSED_FEATURES': 'resnet,clip'})
    rungs = rec['rungs']
    assert 'worklist_fused_error' not in rungs, \
        rungs.get('worklist_fused_error')
    assert any(k.startswith('worklist_fused_clips_per_sec')
               for k in rungs)
    assert rungs['worklist_fused_speedup'] > 0
    # sha256 passes: counter-based and exact — N sequential family
    # passes hash every video, the fused pass hashes each ONCE
    assert rungs['worklist_fused_hash_amortization'] == 2.0
    # decode seconds: timing-based, so only sign-asserted
    assert rungs['worklist_fused_decode_amortization'] > 0
    # the family set behind the number — bench_diff config metadata
    assert rungs['worklist_fused_families'] == 'resnet,clip'
    fused_rep = next(v for k, v in rec['stage_reports'].items()
                     if k.startswith('worklist_fused'))
    # the lead tracer carries the SHARED decode stream's stage
    assert 'decode+preprocess' in fused_rep and 'model' in fused_rep


def test_bench_index_rung_emits_keys():
    """BENCH_INDEX=1 drives the feature-index rung: a served extract
    publishes into the cache, the ingest worker folds it to lag 0, and
    query-by-vector rates through the loopback ``search`` command.
    Recall@10 is a SELF-CHECK, not a measurement — the index is exact,
    so every indexed row must retrieve itself at rank 1 (score 1.0)
    and the rung pins 1.0 by construction."""
    rec = _run_bench({'BENCH_MODE': 'both', 'BENCH_E2E_RUNS': '1',
                      'BENCH_VIDEO': 'synthetic', 'BENCH_E2E_SECONDS': '1',
                      'BENCH_WORKLIST': '1', 'BENCH_SERVE': '0',
                      'BENCH_CACHE': '0', 'BENCH_FUSED': '0',
                      'BENCH_BF16': '0', 'BENCH_INGRESS': '0',
                      'BENCH_WORKLIST_FEATURE': 'resnet',
                      'BENCH_INDEX': '1'})
    rungs = rec['rungs']
    assert 'index_error' not in rungs, rungs.get('index_error')
    assert rungs['index_queries_per_sec'] > 0
    assert rungs['index_recall_at_10'] == 1.0
    assert rungs['index_rows_live'] > 0


def test_bench_fleet_rung_emits_keys():
    """BENCH_FLEET=1 drives the fleet rung (fleet/): two daemons share
    an L2 feature tier and an AOT artifact tier behind the content-hash
    router. The record must carry the fleet-wide warm re-serve rate,
    the shared-store hit rate, and the cold host's boot-to-first-
    feature wall — the rung itself asserts the cold host never compiles
    (artifact-tier pull) and never decodes (peer L2 serve), so an
    ``fleet_error``-free record IS the acceptance evidence."""
    rec = _run_bench({'BENCH_MODE': 'both', 'BENCH_E2E_RUNS': '1',
                      'BENCH_VIDEO': 'synthetic', 'BENCH_E2E_SECONDS': '1',
                      'BENCH_WORKLIST': '1', 'BENCH_SERVE': '0',
                      'BENCH_CACHE': '0', 'BENCH_FUSED': '0',
                      'BENCH_BF16': '0', 'BENCH_INGRESS': '0',
                      'BENCH_INDEX': '0',
                      'BENCH_WORKLIST_FEATURE': 'resnet',
                      'BENCH_FLEET': '1'})
    rungs = rec['rungs']
    assert 'fleet_error' not in rungs, rungs.get('fleet_error')
    assert rungs['fleet_warm_clips_per_sec'] > 0
    assert 0.0 < rungs['fleet_cache_hit_rate'] <= 1.0
    assert rungs['fleet_cold_host_first_feature_s'] > 0
    # direction-awareness downstream: the boot wall is a latency, the
    # rates gate like throughputs
    import tools.bench_diff as bd
    assert bd.lower_is_better('fleet_cold_host_first_feature_s')
    assert not bd.lower_is_better('fleet_warm_clips_per_sec')
    assert not bd.lower_is_better('fleet_cache_hit_rate')


def test_bench_diff_error_rungs_flagged_never_gated(tmp_path):
    """tools/bench_diff.py direction-awareness for the *_error* fields:
    a measured-error rung that RISES shows as WORSE (lower-is-better)
    but never trips --fail-on-regression; speedups gate like any
    throughput rung."""
    import tools.bench_diff as bd
    old = {'metric': 'm', 'value': 1.0, 'unit': 'u', 'vs_baseline': 1.0,
           'rungs': {'worklist_packed_bf16_max_abs_error': 0.001,
                     'worklist_packed_bf16_speedup': 2.0}}
    new = {'metric': 'm', 'value': 1.0, 'unit': 'u', 'vs_baseline': 1.0,
           'rungs': {'worklist_packed_bf16_max_abs_error': 0.01,
                     'worklist_packed_bf16_speedup': 2.0}}
    a, b = tmp_path / 'a.json', tmp_path / 'b.json'
    a.write_text(json.dumps(old))
    b.write_text(json.dumps(new))
    # 10x worse error, threshold 1%: still exit 0 — flagged, never gated
    assert bd.main([str(a), str(b), '--fail-on-regression', '1']) == 0
    # ...but a dropped speedup DOES gate
    new['rungs']['worklist_packed_bf16_speedup'] = 1.0
    b.write_text(json.dumps(new))
    assert bd.main([str(a), str(b), '--fail-on-regression', '10']) == 1
    assert bd.lower_is_better('x_rel_l2_error')
    assert bd.is_error_rung('x_max_abs_error')
    assert not bd.is_error_rung('serve_bf16_speedup')
    # zero-cold-start rungs: boot-to-first-feature is a latency (rises =
    # WORSE), the program hit rate gates like a throughput (drops = WORSE)
    assert bd.lower_is_better('serve_boot_first_feature_s')
    assert bd.lower_is_better('serve_boot_first_feature_cold_s')
    assert not bd.lower_is_better('aot_hit_rate')


def test_bench_serve_rung_emits_keys():
    """BENCH_SERVE=1 drives the warm-pool service rung (serve/): the
    record must carry the sustained + cold clips/sec, the latency
    percentiles, and a warm-pool hit rate > 0 — all while keeping the
    one-JSON-line stdout contract (the server threads print diagnostics
    that must stay on stderr)."""
    rec = _run_bench({'BENCH_MODE': 'both', 'BENCH_E2E_RUNS': '1',
                      'BENCH_VIDEO': 'synthetic', 'BENCH_E2E_SECONDS': '1',
                      'BENCH_SERVE': '1', 'BENCH_WORKLIST': '0',
                      'BENCH_CACHE': '0'})
    rungs = rec['rungs']
    assert 'serve_error' not in rungs, rungs.get('serve_error')
    assert any(k.startswith('serve_clips_per_sec') for k in rungs)
    assert any(k.startswith('serve_cold_clips_per_sec') for k in rungs)
    assert rungs['serve_p50_latency_s'] > 0
    assert rungs['serve_p99_latency_s'] >= rungs['serve_p50_latency_s']
    assert rungs['serve_warm_hit_rate'] > 0


def test_bench_serve_ingress_rung_emits_keys():
    """BENCH_INGRESS=1 drives the network-front-door rung (ingress/):
    one real segment query through HTTP auth/quota/admission, then RTT
    percentiles over the ingress vs the loopback socket — the record
    must carry both pairs (direction-aware: they are *latency* rungs),
    all while keeping the one-JSON-line stdout contract."""
    rec = _run_bench({'BENCH_MODE': 'both', 'BENCH_E2E_RUNS': '1',
                      'BENCH_VIDEO': 'synthetic', 'BENCH_E2E_SECONDS': '1',
                      'BENCH_SERVE': '0', 'BENCH_WORKLIST': '0',
                      'BENCH_CACHE': '0', 'BENCH_INGRESS': '1',
                      'BENCH_INGRESS_RTT_N': '25'})
    rungs = rec['rungs']
    assert 'serve_ingress_error' not in rungs, \
        rungs.get('serve_ingress_error')
    for key in ('serve_ingress_p50_latency_s',
                'serve_ingress_p99_latency_s',
                'serve_ingress_loopback_p50_latency_s',
                'serve_ingress_loopback_p99_latency_s'):
        assert rungs[key] > 0, (key, rungs)
    assert rungs['serve_ingress_p99_latency_s'] >= \
        rungs['serve_ingress_p50_latency_s']


def test_bench_aot_rung_emits_keys():
    """BENCH_AOT=1 drives the zero-cold-start rung (aot/): two daemon
    boots against one persistent executable store — the record must
    carry boot-to-first-feature for the cold-store boot (pays XLA
    compiles) and the warm-store boot (loads serialized executables;
    asserted compile-free inside the rung), plus the warm boot's
    program hit rate — all while keeping the one-JSON-line stdout
    contract."""
    rec = _run_bench({'BENCH_MODE': 'both', 'BENCH_E2E_RUNS': '1',
                      'BENCH_VIDEO': 'synthetic', 'BENCH_E2E_SECONDS': '1',
                      'BENCH_SERVE': '0', 'BENCH_WORKLIST': '0',
                      'BENCH_CACHE': '0', 'BENCH_AOT': '1'})
    rungs = rec['rungs']
    assert 'serve_aot_error' not in rungs, rungs.get('serve_aot_error')
    assert rungs['serve_boot_first_feature_s'] > 0
    assert rungs['serve_boot_first_feature_cold_s'] > 0
    # every pre-warmed program loaded on the warm-store boot
    assert rungs['aot_hit_rate'] == 1.0, rungs


def test_bench_cache_rung_emits_keys():
    """BENCH_CACHE=1 drives the content-addressed cache rung (cache/):
    the record must carry cold vs warm-hit clips/sec, the per-video hit
    latency, a hit rate > 0, and bytes saved — the warm number must beat
    the cold one (a hit is an O(read) copy vs decode + inference), all
    while keeping the one-JSON-line stdout contract."""
    rec = _run_bench({'BENCH_MODE': 'both', 'BENCH_E2E_RUNS': '1',
                      'BENCH_VIDEO': 'synthetic', 'BENCH_E2E_SECONDS': '1',
                      'BENCH_SERVE': '0', 'BENCH_WORKLIST': '0',
                      'BENCH_CACHE': '1'})
    rungs = rec['rungs']
    assert 'cache_error' not in rungs, rungs.get('cache_error')
    cold = next(rungs[k] for k in rungs
                if k.startswith('cache_cold_clips_per_sec'))
    warm = next(rungs[k] for k in rungs
                if k.startswith('cache_hit_clips_per_sec'))
    assert warm > cold, (cold, warm)
    assert rungs['cache_hit_latency_s'] > 0
    assert rungs['cache_hit_rate'] > 0
    assert rungs['cache_bytes_saved'] > 0
