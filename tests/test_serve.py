"""The warm-pool extraction service (serve/): the long-running serving
layer must preserve every batch-path contract — byte-identical outputs vs
the one-shot CLI, per-video fault isolation inside shared batches, the
resume skip — while adding warmth (transplant+compile paid once across
requests), admission control, deadlines, and graceful drain.

Socket-level tests run a real server on an ephemeral loopback port with
resnet18 random weights on CPU (same fixture weight class as
tests/test_packing.py). Soak-style concurrency tests are ``slow``.
"""
import os
import signal
import time
from pathlib import Path

import numpy as np
import pytest

from video_features_tpu.utils.output import make_path


from tools.make_sample_video import write_noise_clip as _write_clip  # noqa: E402


@pytest.fixture(scope='module')
def serve_clips(tmp_path_factory):
    d = tmp_path_factory.mktemp('servevids')
    return [_write_clip(d / f'sv{i}.mp4', n, seed=i)
            for i, n in enumerate((9, 4))]


def _base_overrides(tmp_path):
    return {
        'device': 'cpu', 'model_name': 'resnet18', 'batch_size': 4,
        'allow_random_weights': True, 'on_extraction': 'save_numpy',
        'tmp_path': str(tmp_path / 'serve_tmp'),
    }


def _start_server(tmp_path, **kw):
    from video_features_tpu.serve.server import ExtractionServer
    opts = dict(base_overrides=_base_overrides(tmp_path), queue_depth=32,
                pool_size=2)
    opts.update(kw)
    return ExtractionServer(**opts).start()


RESNET_KEYS = ('resnet', 'fps', 'timestamps_ms')


# -- pure units (no server, no jax) ------------------------------------------


def test_check_version_minor_skew_accepted_major_rejected():
    """The MAJOR/MINOR compatibility contract behind WIRE.lock.json's
    bump semantics: VERSION is now '1.5' (1.1 covered PR 8's versioning
    + PR 11's trace surface; 1.2 added the additive `features` fused
    submit field; 1.3 adds the `search`/`index_status` feature-index
    surface; 1.4 adds the additive `code` error field the fleet
    router's failover keys on; 1.5 adds the additive fleet
    observability surface — scatter-gathered traces with `hosts`,
    aggregated `/metrics`, `vft_slo_*`), and a client speaking ANY
    unknown 1.x
    must keep working, while an unknown major gets the structured
    rejection echoing its request_id."""
    from video_features_tpu.serve import protocol

    assert protocol.VERSION == '1.5'
    assert protocol.MAJOR == 1
    # minor skew is additive-fields-only by contract: never rejected,
    # future minors included
    assert protocol.check_version({'v': '1.0'}) is None
    assert protocol.check_version({'v': '1.1'}) is None
    assert protocol.check_version({'v': '1.7'}) is None
    # pre-versioning clients (no v) keep working
    assert protocol.check_version({'cmd': 'ping'}) is None
    # unknown MAJOR: structured error naming both versions and echoing
    # the message's request_id for client-side correlation
    rej = protocol.check_version({'v': '2.0', 'request_id': 'r000042'})
    assert rej is not None and rej['ok'] is False
    assert '2.0' in rej['error'] and protocol.VERSION in rej['error']
    assert rej['v'] == protocol.VERSION
    assert rej['request_id'] == 'r000042'
    # malformed versions fail loudly too, not as a parse error
    assert protocol.check_version({'v': 'banana'})['ok'] is False

def test_warm_pool_lru_hit_rate_and_graceful_eviction():
    from video_features_tpu.serve.pool import WarmPool

    class FakeEntry:
        def __init__(self, busy=False):
            self.busy = busy
            self.closed = False

        def idle(self):
            return not self.busy

        def close(self):
            self.closed = True

    pool = WarmPool(2)
    a, b, c = FakeEntry(), FakeEntry(), FakeEntry()
    assert pool.get(('a',)) is None            # miss
    pool.put(('a',), a)
    pool.put(('b',), b)
    assert pool.get(('a',)) is a               # hit refreshes recency
    evicted = pool.put(('c',), c)              # b is now LRU → evicted
    assert evicted == [b] and b.closed
    st = pool.stats()
    assert st['size'] == 2 and st['evictions'] == 1
    assert st['hits'] == 1 and st['misses'] == 1 and st['hit_rate'] == 0.5

    # a busy LRU entry is passed over: pool runs over capacity rather
    # than stalling admission behind a drain
    a.busy = True
    c.busy = True
    d = FakeEntry()
    assert pool.put(('d',), d) == []
    assert pool.stats()['size'] == 3
    a.busy = False
    e = FakeEntry()
    # back under capacity: BOTH idle entries (a: LRU, d) evict; busy c
    # stays over-capacity until it goes idle
    assert set(pool.put(('e',), e)) == {a, d}
    assert pool.stats()['size'] == 2


def test_packed_batches_flush_sentinel():
    """FLUSH forces partial geometry pools out padded — the latency bound
    for a lone request during an arrival lull — and later windows of the
    same geometry pool afresh. Every FLUSH is followed by the batchless
    drain marker ``(None, [], 0)`` so the consumer also materializes its
    in-flight output queue (async device loop) on idle."""
    from video_features_tpu.parallel.packing import FLUSH, packed_batches

    w = np.zeros((2, 2), np.float32)

    def stream():
        yield ('t1', w, None)
        yield FLUSH
        yield FLUSH                            # idempotent on empty pools
        yield ('t2', w, None)
        yield ('t3', w, None)

    out = list(packed_batches(stream(), batch=2))
    markers = [item for item in out if item[0] is None]
    assert markers == [(None, [], 0)] * 2      # one drain marker per FLUSH
    batches = [item for item in out if item[0] is not None]
    assert [(v, [t for t, _ in prov]) for _, prov, v in batches] == \
        [(1, ['t1']), (2, ['t2', 't3'])]
    # the first FLUSH's flushed batch precedes its drain marker
    assert out[0][0] is not None and out[1][0] is None
    assert all(stacks.shape == (2, 2, 2) for stacks, _, _ in batches)


def test_packed_batches_pool_age_bound():
    """Under CONTINUOUS traffic the feed never idles (no FLUSH), but a
    partial pool older than max_pool_age_s must still flush as other
    geometries' windows keep flowing — the serve liveness bound."""
    import time as _t

    from video_features_tpu.parallel.packing import packed_batches

    odd = np.zeros((3, 3), np.float32)
    main = np.zeros((2, 2), np.float32)

    def stream():
        yield ('odd', odd, None)               # pools alone
        _t.sleep(0.06)
        for i in range(4):                     # other-geometry traffic
            yield (f'm{i}', main, None)

    out = list(packed_batches(stream(), batch=4, max_pool_age_s=0.05))
    # the odd window flushed (padded, valid=1) BEFORE the main batch
    # completed — it did not wait for stream end
    assert [(v, [t for t, _ in prov]) for _, prov, v in out] == \
        [(1, ['odd']), (4, ['m0', 'm1', 'm2', 'm3'])]


def test_atomic_writes_leave_no_partial_files(tmp_path):
    from video_features_tpu.utils.output import (
        load_numpy, load_pickle, write_numpy, write_pickle,
    )

    fp = str(tmp_path / 'a.npy')
    write_numpy(fp, np.arange(5))
    np.testing.assert_array_equal(load_numpy(fp), np.arange(5))
    pp = str(tmp_path / 'b.pkl')
    write_pickle(pp, {'x': 1})
    assert load_pickle(pp) == {'x': 1}

    # a crash mid-write must strand nothing at the final path and clean
    # its tmp; a previously published file must survive untouched
    class Dies:
        def __reduce__(self):
            raise RuntimeError('dies mid-pickle')

    with pytest.raises(RuntimeError):
        write_pickle(pp, Dies())
    assert load_pickle(pp) == {'x': 1}
    assert [f.name for f in tmp_path.iterdir()] != []
    assert not [f for f in tmp_path.iterdir() if f.suffix == '.tmp']


def test_split_serve_config_validates():
    from video_features_tpu.config import split_serve_config

    serve, base = split_serve_config({
        'serve_port': '8791', 'serve_queue_depth': 8,
        'device': 'cpu', 'batch_size': 4,
    })
    assert serve['serve_port'] == 8791 and serve['serve_queue_depth'] == 8
    assert serve['serve_warm_pool_size'] == 4        # default survives
    assert base == {'device': 'cpu', 'batch_size': 4}
    with pytest.raises(ValueError, match='serve_warm_pol'):
        split_serve_config({'serve_warm_pol_size': 2})   # typo'd knob
    with pytest.raises(ValueError, match='serve_queue_depth'):
        split_serve_config({'serve_queue_depth': 0})


def test_tracer_merge_reports():
    from video_features_tpu.utils.tracing import Tracer, merge_reports

    t1, t2 = Tracer(), Tracer()
    t1.add('model', 1.0)
    t1.add('model', 3.0)
    t1.add_occupancy('model', 3, 4)
    t2.add('model', 2.0)
    t2.add_occupancy('model', 4, 4)
    t2.add('decode', 5.0)
    m = merge_reports([t1.report(), t2.report()])
    assert m['model']['count'] == 3
    assert m['model']['total_s'] == pytest.approx(6.0)
    assert m['model']['max_s'] == pytest.approx(3.0)
    assert m['model']['occupancy'] == pytest.approx(7 / 8)
    assert m['decode']['count'] == 1


def test_device_placer_stacks_int8_quarter_size_entries():
    """The precision ladder's serve payoff, pinned with NO placer code
    change: int8 entries are ~quarter the fp32 params bytes, so the
    byte-first ranking stacks TWO int8 entries plus a bf16 entry on one
    chip before a second fp32 copy lands there — and the
    ``vft_device_resident_bytes`` gauges read the QUANTIZED residency,
    not a per-entry count."""
    import jax

    from video_features_tpu.serve.pool import DevicePlacer

    devices = jax.devices()[:2]
    placer = DevicePlacer()
    FP32, BF16, INT8 = 4000, 2000, 1000     # the ladder's byte ratios
    fp32_a = placer.assign(devices, 1, nbytes=FP32)
    int8_a = placer.assign(devices, 1, nbytes=INT8)
    int8_b = placer.assign(devices, 1, nbytes=INT8)
    bf16_a = placer.assign(devices, 1, nbytes=BF16)
    # the small-lane chip absorbs both int8 entries AND the bf16 entry
    # (1000+1000+2000 = 4000 bytes) before the fp32 chip takes anything
    # else — byte ranking, where entry-count ranking would have
    # alternated chips after the first int8 landed
    assert int8_a[0].id != fp32_a[0].id
    assert int8_b[0].id == int8_a[0].id
    assert bf16_a[0].id == int8_a[0].id
    by_bytes = placer.snapshot_bytes()
    assert by_bytes[f'd{fp32_a[0].id}'] == FP32
    assert by_bytes[f'd{int8_a[0].id}'] == 2 * INT8 + BF16
    # now the ledger is level (4000 vs 4000): the NEXT fp32 copy ties on
    # bytes, ties on nothing else but entry count (1 vs 3) — it lands on
    # the fp32 chip, keeping the quantized stack intact
    fp32_b = placer.assign(devices, 1, nbytes=FP32)
    assert fp32_b[0].id == fp32_a[0].id
    for entry, size in ((fp32_a, FP32), (fp32_b, FP32), (bf16_a, BF16),
                        (int8_a, INT8), (int8_b, INT8)):
        placer.release(entry, nbytes=size)
    assert set(placer.snapshot_bytes().values()) == {0}
    assert set(placer.snapshot().values()) == {0}


# -- the live server ---------------------------------------------------------

def test_serve_lifecycle_warm_parity_fault_sigterm_resume(
        serve_clips, tmp_path, monkeypatch):
    """The acceptance path, end to end over the real socket:

    1. a warm server extracts the same two-video worklist twice paying
       transplant exactly once (pool hit rate > 0, one extractor build);
    2. outputs are byte-identical to the one-shot CLI path;
    3. a mid-queue failing video fails alone — its batch-mates save;
    4. a real SIGTERM drains gracefully, losing no completed output;
    5. a restarted server resumes: completed videos skip.
    """
    import video_features_tpu.serve.server as server_mod
    from video_features_tpu.serve.client import ServeClient

    builds = []
    real_create = server_mod.create_extractor
    monkeypatch.setattr(server_mod, 'create_extractor',
                        lambda args: builds.append(args['feature_type'])
                        or real_create(args))

    server = _start_server(tmp_path)
    client = ServeClient(port=server.port)
    assert client.ping()

    # -- 1+2: two passes, one transplant, CLI-parity outputs
    out1, out2 = str(tmp_path / 'p1'), str(tmp_path / 'p2')
    for out_root in (out1, out2):
        rid = client.submit('resnet', serve_clips,
                            overrides={'output_path': out_root})
        st = client.wait(rid, timeout_s=180)
        assert st['state'] == 'done', st
        assert set(st['videos'].values()) == {'saved'}
    assert builds == ['resnet']                # warm: built exactly once
    m = client.metrics()
    assert m['warm_pool']['hit_rate'] > 0
    assert m['warm_pool']['misses'] == 1
    assert m['requests']['completed'] == 2
    assert m['latency']['p99_s'] is not None
    assert m['stages_merged']['model']['count'] > 0

    from video_features_tpu.config import load_config
    from video_features_tpu.registry import create_extractor
    ref = create_extractor(load_config('resnet', overrides=dict(
        _base_overrides(tmp_path), video_paths=serve_clips,
        output_path=str(tmp_path / 'ref'),
        tmp_path=str(tmp_path / 'ref_tmp'))))
    for p in serve_clips:
        ref._extract(p)
    for p in serve_clips:
        for key in RESNET_KEYS:
            a = Path(make_path(ref.output_path, p, key, '.npy'))
            b = Path(make_path(os.path.join(out1, 'resnet', 'resnet18'),
                               p, key, '.npy'))
            assert a.read_bytes() == b.read_bytes(), (p, key)

    # -- 3: mid-queue failing video + 4: SIGTERM drain, in flight together
    old_term = signal.getsignal(signal.SIGTERM)
    old_int = signal.getsignal(signal.SIGINT)
    try:
        server.install_signal_handlers()
        bad = str(tmp_path / 'missing.mp4')    # never created
        out3 = str(tmp_path / 'p3')
        rid3 = client.submit(
            'resnet', [serve_clips[0], bad, serve_clips[1]],
            overrides={'output_path': out3})
        os.kill(os.getpid(), signal.SIGTERM)   # drain while rid3 queued
        deadline = time.monotonic() + 120
        while not server.drained and time.monotonic() < deadline:
            time.sleep(0.05)
        assert server.drained
    finally:
        signal.signal(signal.SIGTERM, old_term)
        signal.signal(signal.SIGINT, old_int)

    st3 = server.status(rid3)                  # in-process: socket is down
    assert st3['state'] == 'partial', st3
    assert st3['videos'][bad] == 'failed'
    out3_root = os.path.join(out3, 'resnet', 'resnet18')
    for p in serve_clips:                      # batch-mates survived drain
        assert st3['videos'][p] == 'saved'
        for key in RESNET_KEYS:
            assert Path(make_path(out3_root, p, key, '.npy')).exists()
    with pytest.raises(Exception):             # draining rejects admission
        client.submit('resnet', serve_clips,
                      overrides={'output_path': str(tmp_path / 'px')})

    # -- 5: restart + resume: completed outputs skip, nothing rewritten
    mtimes = {p: Path(make_path(out3_root, p, 'resnet', '.npy'))
              .stat().st_mtime_ns for p in serve_clips}
    server2 = _start_server(tmp_path)
    try:
        client2 = ServeClient(port=server2.port)
        rid4 = client2.submit('resnet', serve_clips,
                              overrides={'output_path': out3})
        st4 = client2.wait(rid4, timeout_s=180)
        assert st4['state'] == 'done'
        assert set(st4['videos'].values()) == {'skipped'}
        for p in serve_clips:
            assert Path(make_path(out3_root, p, 'resnet', '.npy')) \
                .stat().st_mtime_ns == mtimes[p]
    finally:
        server2.drain(wait=True, grace_s=60)


def test_serve_async_loop_parity_and_inflight_gauge(serve_clips, tmp_path):
    """The warm workers inherit the async device loop: a server pinned
    synchronous (inflight=1 base override) and one running the
    deferred-D2H loop (inflight=2) produce BYTE-identical outputs for
    the same request, and the metrics document carries the
    vft_inflight_batches gauge (0 once idle — every dispatched batch
    was materialized)."""
    from video_features_tpu.serve.client import ServeClient

    roots = {}
    for depth in (1, 2):
        server = _start_server(
            tmp_path, base_overrides=dict(_base_overrides(tmp_path),
                                          inflight=depth))
        try:
            client = ServeClient(port=server.port)
            out_root = str(tmp_path / f'async{depth}')
            rid = client.submit('resnet', serve_clips,
                                overrides={'output_path': out_root})
            st = client.wait(rid, timeout_s=180)
            assert st['state'] == 'done', st
            m = client.metrics()
            assert m['inflight_batches'] == 0   # drained back to idle
            prom = client.metrics_prom()
            assert 'vft_inflight_batches 0' in prom
        finally:
            server.drain(wait=True, grace_s=60)
        roots[depth] = os.path.join(out_root, 'resnet', 'resnet18')

    compared = 0
    for p in serve_clips:
        for key in RESNET_KEYS:
            a = Path(make_path(roots[1], p, key, '.npy'))
            b = Path(make_path(roots[2], p, key, '.npy'))
            assert a.read_bytes() == b.read_bytes(), (p, key)
            compared += 1
    assert compared == len(serve_clips) * len(RESNET_KEYS)


def test_serve_admission_deadline_and_protocol_errors(
        serve_clips, tmp_path):
    from video_features_tpu.serve.client import ServeClient, ServeError

    server = _start_server(tmp_path, queue_depth=2)
    try:
        client = ServeClient(port=server.port)
        # backpressure: a request that would exceed queue depth is
        # REJECTED atomically (not partially admitted)
        with pytest.raises(ServeError, match='queue_full'):
            client.submit('resnet', [str(tmp_path / f'x{i}.mp4')
                                     for i in range(3)],
                          overrides={'output_path': str(tmp_path / 'o')})
        # duplicate paths would collapse in per-request accounting —
        # rejected even under `python -O` (where sanity_check's
        # unique-stem assert vanishes)
        with pytest.raises(ServeError, match='duplicate'):
            client.submit('resnet', [serve_clips[0], serve_clips[0]],
                          overrides={'output_path': str(tmp_path / 'o')})
        # no packed support → no serving support, rejected loudly
        with pytest.raises(ServeError, match='vggish'):
            client.submit('vggish', serve_clips,
                          overrides={'output_path': str(tmp_path / 'o')})
        # invalid per-request config surfaces the sanity_check reason
        with pytest.raises(ServeError, match='invalid request'):
            client.submit('resnet', serve_clips,
                          overrides={'output_path': str(tmp_path / 'same'),
                                     'tmp_path': str(tmp_path / 'same')})
        # an already-expired deadline: videos expire unstarted, the
        # request still reaches a terminal state
        rid = client.submit('resnet', serve_clips, timeout_s=0.0,
                            overrides={'output_path': str(tmp_path / 'od')})
        st = client.wait(rid, timeout_s=120)
        assert st['state'] == 'failed'
        assert set(st['videos'].values()) == {'expired'}
        m = client.metrics()
        assert m['requests']['expired_videos'] == len(serve_clips)
        assert m['requests']['rejected'] == 4
        # protocol-level garbage gets an error reply, not a hang
        with pytest.raises(ServeError, match='unknown cmd'):
            client._call({'cmd': 'frobnicate'})
        with pytest.raises(ServeError, match='unknown request_id'):
            client.status('r999999')
        with pytest.raises(ServeError, match='unknown submit fields'):
            client._call({'cmd': 'submit', 'feature_type': 'resnet',
                          'video_paths': serve_clips, 'surprise': 1})
    finally:
        server.drain(wait=True, grace_s=60)


@pytest.mark.slow
def test_serve_soak_concurrent_requests_and_metrics_file(
        serve_clips, tmp_path):
    """Soak: concurrent clients race submits through one warm worker;
    every request reaches a terminal state, outputs parity-match a clean
    packed run, and the metrics mirror file stays valid JSON."""
    import json
    import threading

    from video_features_tpu.serve.client import ServeClient

    metrics_path = str(tmp_path / 'metrics.json')
    server = _start_server(tmp_path, queue_depth=64,
                           metrics_path=metrics_path)
    try:
        results = {}

        def one_client(i):
            c = ServeClient(port=server.port)
            out_root = str(tmp_path / f'soak{i}')
            rid = c.submit('resnet', serve_clips,
                           overrides={'output_path': out_root})
            results[i] = (out_root, c.wait(rid, timeout_s=300))

        threads = [threading.Thread(target=one_client, args=(i,))
                   for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(300)
        assert len(results) == 4
        first_root = None
        for i, (out_root, st) in sorted(results.items()):
            assert st['state'] == 'done', (i, st)
            root = os.path.join(out_root, 'resnet', 'resnet18')
            if first_root is None:
                first_root = root
                continue
            for p in serve_clips:
                for key in RESNET_KEYS:
                    a = Path(make_path(first_root, p, key, '.npy'))
                    b = Path(make_path(root, p, key, '.npy'))
                    assert a.read_bytes() == b.read_bytes(), (i, p, key)
        doc = json.loads(Path(metrics_path).read_text())
        assert doc['requests']['completed'] == 4
        # concurrent cold submits may each count a miss, but the per-key
        # build lock guarantees ONE transplant total (no aot store in
        # this config, so the build lands on the compiled counter)
        assert doc['warm_pool']['builds_compiled'] == 1
        assert doc['warm_pool']['builds_loaded'] == 0
    finally:
        server.drain(wait=True, grace_s=60)
