"""Config system: YAML defaults, dotlist merge, sanity_check behavior parity."""
import os

import pytest

from video_features_tpu.config import (
    Config, form_list_from_user_input, load_config, parse_dotlist, sanity_check,
)


def _mk_video(tmp_path, name='vid.mp4'):
    p = tmp_path / name
    p.write_bytes(b'\x00')
    return str(p)


def test_parse_dotlist_yaml_typing():
    cfg = parse_dotlist([
        'feature_type=i3d', 'stack_size=24', 'extraction_fps=null',
        'keep_tmp_files=true', "video_paths=['a.mp4','b.mp4']",
    ])
    assert cfg.feature_type == 'i3d'
    assert cfg.stack_size == 24 and isinstance(cfg.stack_size, int)
    assert cfg.extraction_fps is None
    assert cfg.keep_tmp_files is True
    assert cfg.video_paths == ['a.mp4', 'b.mp4']


def test_load_config_defaults_and_override(tmp_path):
    v = _mk_video(tmp_path)
    args = load_config('i3d', overrides={'video_paths': v, 'stack_size': 24,
                                         'device': 'cpu'})
    assert args.feature_type == 'i3d'
    assert args.stack_size == 24
    assert args.step_size == 16  # YAML default survives
    # path rewriting appends feature_type
    assert args.output_path.endswith(os.path.join('output', 'i3d'))
    assert args.tmp_path.endswith(os.path.join('tmp', 'i3d'))


def test_model_name_appended_with_slash_replaced(tmp_path):
    v = _mk_video(tmp_path)
    args = load_config('clip', overrides={'video_paths': v, 'device': 'cpu'})
    assert args.output_path.endswith(os.path.join('output', 'clip', 'ViT-B_32'))


def test_unknown_feature_type():
    with pytest.raises(NotImplementedError):
        load_config('pwc2')


def test_sanity_rejects_missing_paths():
    with pytest.raises(AssertionError):
        load_config('i3d', overrides={'device': 'cpu'})


def test_sanity_rejects_duplicate_stems(tmp_path):
    a = tmp_path / 'a';  a.mkdir()
    b = tmp_path / 'b';  b.mkdir()
    v1 = _mk_video(a, 'same.mp4')
    v2 = _mk_video(b, 'same.mp4')
    with pytest.raises(AssertionError):
        load_config('resnet', overrides={'video_paths': [v1, v2], 'device': 'cpu'})


def test_sanity_rejects_small_i3d_stack(tmp_path):
    v = _mk_video(tmp_path)
    with pytest.raises(AssertionError):
        load_config('i3d', overrides={'video_paths': v, 'stack_size': 4,
                                      'device': 'cpu'})


def test_sanity_rejects_pwc(tmp_path):
    v = _mk_video(tmp_path)
    with pytest.raises(NotImplementedError):
        load_config('i3d', overrides={'video_paths': v, 'flow_type': 'pwc',
                                      'device': 'cpu'})


def test_sanity_rejects_fps_and_total(tmp_path):
    v = _mk_video(tmp_path)
    with pytest.raises(AssertionError):
        load_config('resnet', overrides={'video_paths': v, 'extraction_fps': 5,
                                         'extraction_total': 10, 'device': 'cpu'})


def test_sanity_rejects_same_out_and_tmp(tmp_path):
    v = _mk_video(tmp_path)
    with pytest.raises(AssertionError):
        load_config('resnet', overrides={'video_paths': v, 'output_path': './x',
                                         'tmp_path': './x', 'device': 'cpu'})


def test_timm_requires_model_name(tmp_path):
    v = _mk_video(tmp_path)
    with pytest.raises(AssertionError):
        load_config('timm', overrides={'video_paths': v, 'device': 'cpu'})


def test_device_never_leaks_cuda(tmp_path):
    # 'cuda:0' (torch-style) maps to the accelerator if present, else cpu.
    v = _mk_video(tmp_path)
    args = load_config('resnet', overrides={'video_paths': v, 'device': 'cuda:0'})
    assert args.device in ('cpu', 'tpu')


def test_device_cpu_stays_cpu(tmp_path):
    v = _mk_video(tmp_path)
    args = load_config('resnet', overrides={'video_paths': v, 'device': 'cpu'})
    assert args.device == 'cpu'


def test_form_list_from_file(tmp_path):
    v1 = _mk_video(tmp_path, 'a.mp4')
    v2 = _mk_video(tmp_path, 'b.mp4')
    listfile = tmp_path / 'list.txt'
    listfile.write_text(f'{v1}\n\n{v2}\n')
    paths = form_list_from_user_input(None, str(listfile), to_shuffle=False)
    assert paths == [v1, v2]


def test_config_attr_access():
    c = Config(a=1)
    assert c.a == 1
    c.b = 2
    assert c['b'] == 2
    with pytest.raises(AttributeError):
        _ = c.missing


def test_precision_validated(tmp_path):
    v = _mk_video(tmp_path)
    args = load_config('resnet', overrides={
        'video_paths': v, 'device': 'cpu', 'precision': 'default'})
    assert args.precision == 'default'
    # ValueError (not assert) so validation survives `python -O`
    with pytest.raises(ValueError, match='precision'):
        load_config('resnet', overrides={
            'video_paths': v, 'device': 'cpu', 'precision': 'fp8'})


def test_pack_fallback_warns_off_stdout(tmp_path, capsys):
    """The pack_across_videos degradations must go through warnings.warn
    (stderr), NOT print: with on_extraction=print the feature stream owns
    stdout and an interleaved WARNING line breaks its parsers."""
    v = _mk_video(tmp_path)
    with pytest.warns(UserWarning, match='not implemented for vggish'):
        args = load_config('vggish', overrides={
            'video_paths': v, 'device': 'cpu', 'pack_across_videos': True})
    assert args['pack_across_videos'] is False
    assert 'WARNING' not in capsys.readouterr().out

    with pytest.warns(UserWarning, match='show_pred is incompatible'):
        args = load_config('resnet', overrides={
            'video_paths': v, 'device': 'cpu', 'model_name': 'resnet18',
            'pack_across_videos': True, 'show_pred': True})
    assert args['pack_across_videos'] is False
    assert 'WARNING' not in capsys.readouterr().out


def test_precision_reaches_extractor(tmp_path):
    from video_features_tpu.registry import create_extractor
    v = _mk_video(tmp_path)
    args = load_config('resnet', overrides={
        'video_paths': v, 'device': 'cpu', 'batch_size': 2,
        'precision': 'default', 'compilation_cache_dir': None})
    ex = create_extractor(args)
    assert ex.precision == 'default'
