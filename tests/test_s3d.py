"""S3D: numerical parity vs the reference torch net + E2E extraction."""
import numpy as np
import pytest
import torch

from video_features_tpu.config import load_config
from video_features_tpu.models import s3d as s3d_model
from video_features_tpu.registry import create_extractor
from video_features_tpu.transplant.torch2jax import transplant


@pytest.fixture(scope='module')
def torch_s3d(reference_repo):
    from models.s3d.s3d_src.s3d import S3D
    torch.manual_seed(0)
    model = S3D(num_class=400)
    model.eval()
    return model


@pytest.mark.slow
def test_parity_vs_reference_torch(torch_s3d):
    """Random-weight transplant: our forward must match torch to float32 noise.

    This is the core de-risking test for the whole torch->JAX transplant
    approach (SURVEY.md §4c): same weights, same input => same features.
    """
    params = transplant(torch_s3d.state_dict())
    rng = np.random.RandomState(0)
    x = rng.rand(1, 16, 64, 64, 3).astype(np.float32)

    with torch.no_grad():
        # torch layout (B, C, T, H, W)
        ref = torch_s3d(torch.from_numpy(x).permute(0, 4, 1, 2, 3),
                        features=True).numpy()
    import jax
    with jax.default_matmul_precision('highest'):
        ours = np.asarray(s3d_model.forward(params, x, features=True))

    assert ours.shape == ref.shape == (1, 1024)
    l2 = np.linalg.norm(ours - ref) / max(np.linalg.norm(ref), 1e-12)
    assert l2 < 1e-3, f'relative L2 {l2}'
    np.testing.assert_allclose(ours, ref, atol=5e-4)


def test_parity_logits(torch_s3d):
    params = transplant(torch_s3d.state_dict())
    rng = np.random.RandomState(1)
    x = rng.rand(1, 16, 64, 64, 3).astype(np.float32)
    with torch.no_grad():
        ref = torch_s3d(torch.from_numpy(x).permute(0, 4, 1, 2, 3),
                        features=False).numpy()
    import jax
    with jax.default_matmul_precision('highest'):
        ours = np.asarray(s3d_model.forward(params, x, features=False))
    assert ours.shape == (1, 400)
    np.testing.assert_allclose(ours, ref, atol=5e-4)


@pytest.mark.slow
def test_e2e_extraction(short_video, tmp_path):
    args = load_config('s3d', overrides={
        'video_paths': short_video,
        'device': 'cpu',
        'stack_size': 16, 'step_size': 16,
        'extraction_fps': None,  # avoid re-encode in tests
        'output_path': str(tmp_path / 'out'),
        'tmp_path': str(tmp_path / 'tmp'),
    })
    ex = create_extractor(args)
    feats = ex.extract(short_video)['s3d']
    assert feats.shape == (3, 1024)
    assert np.isfinite(feats).all()


@pytest.mark.slow
def test_too_small_stack_clear_error():
    """stack_size < 16 leaves < 2 temporal positions at the head — must
    fail with a clear message, not an opaque reshape ZeroDivisionError."""
    params = transplant(s3d_model.init_state_dict())
    x = np.zeros((1, 8, 224, 224, 3), np.float32)
    with pytest.raises(ValueError, match='stack_size >= 16'):
        s3d_model.forward(params, x)
