"""vft-programs (video_features_tpu/analysis/programs.py): the program
contract checker itself.

Three layers, mirroring tests/test_analysis.py:

  * toy jitted functions with PLANTED violations, one per rule — the
    signature extraction + rule pass must catch each (and must NOT fire
    on the clean variant);
  * lock semantics on a real family (r21d — the cheapest build):
    ``--write-lock`` idempotence, injected dtype drift → exit 2, stale /
    unknown lock entries reported;
  * the live-tree gate: the cheap families checked against the SHIPPED
    ``PROGRAMS.lock.json`` in tier-1, all eight in the slow lane — the
    same gate CI's ``programs-check`` job enforces.

Plus the float32-boundary parity assertions the no-f64 rule leans on
(vggish's explicit host-side narrowing must equal jax's old implicit
device_put downcast; host transforms must preserve uint8).
"""
import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from video_features_tpu.analysis.core import EXIT_CLEAN, EXIT_FINDINGS
from video_features_tpu.analysis.programs import (
    FAMILIES, ProgramSpec, build_family, check_program, collect,
    default_lock_path, diff_lock, family_lock_hashes, load_lock, main,
    program_signature, write_lock,
)
from video_features_tpu.parallel.mesh import make_mesh


def sig_and_findings(spec, family='toy', width=1, mesh=None):
    sig = program_signature(spec)
    return sig, check_program(spec, sig, family, width, mesh)


def rules_of(findings):
    return {f.rule for f in findings}


P = jax.ShapeDtypeStruct((), np.float32)
B4 = jax.ShapeDtypeStruct((4, 8), np.uint8)


# -- per-rule toys -----------------------------------------------------------

def test_clean_toy_has_no_findings_and_full_signature():
    f = jax.jit(lambda p, b: b.astype(np.float32).sum(axis=1) * p)
    sig, findings = sig_and_findings(ProgramSpec('step', f, (P, B4)))
    assert findings == []
    assert sig['batch'] == {'shape': [4, 8], 'dtype': 'uint8'}
    assert sig['out'] == [{'shape': [4], 'dtype': 'float32'}]
    assert sig['batch_donated'] is False
    assert sig['const_bytes'] == 0
    assert sig['num_partitions'] == 1
    assert len(sig['stablehlo_sha256']) == 64


def test_no_f64_rule_catches_planted_promotion():
    from jax.experimental import enable_x64
    with enable_x64():
        f = jax.jit(lambda p, b: b.astype(np.float64).sum() * p)
        _, findings = sig_and_findings(ProgramSpec('step', f, (P, B4)))
    assert rules_of(findings) == {'no-f64'}


def test_no_weak_type_rule_catches_scalar_only_epilogue():
    f = jax.jit(lambda p, b: jnp.sin(1.0))
    _, findings = sig_and_findings(ProgramSpec('step', f, (P, B4)))
    assert rules_of(findings) == {'no-weak-type'}


def test_no_host_callback_rule():
    def cb(x):
        return np.asarray(x)

    f = jax.jit(lambda p, b: jax.pure_callback(
        cb, jax.ShapeDtypeStruct(b.shape, np.float32), b))
    _, findings = sig_and_findings(ProgramSpec('step', f, (P, B4)))
    assert 'no-host-callback' in rules_of(findings)


def test_donation_rule_both_directions():
    donated = jax.jit(lambda p, b: b.astype(np.float32).sum() * p,
                      donate_argnums=(1,))
    plain = jax.jit(lambda p, b: b.astype(np.float32).sum() * p)
    # program donates, spec says it must not
    _, findings = sig_and_findings(ProgramSpec('step', donated, (P, B4)))
    assert rules_of(findings) == {'donation'}
    # spec expects donation, program dropped it
    _, findings = sig_and_findings(
        ProgramSpec('step', plain, (P, B4), donate_batch=True))
    assert rules_of(findings) == {'donation'}
    # declared + lowered agree
    sig, findings = sig_and_findings(
        ProgramSpec('step', donated, (P, B4), donate_batch=True))
    assert findings == [] and sig['batch_donated'] is True


def test_shardable_rule_names_indivisible_batch():
    f = jax.jit(lambda p, b: b.astype(np.float32).sum(axis=1) * p)
    odd = jax.ShapeDtypeStruct((3, 8), np.uint8)
    mesh = make_mesh(n_devices=2, time_parallel=1)
    _, findings = sig_and_findings(ProgramSpec('step', f, (P, odd)),
                                   width=2, mesh=mesh)
    assert rules_of(findings) == {'shardable'}
    assert 'cannot shard over 2' in findings[0].message


def test_const_budget_rule_catches_closure_captured_weights():
    weights = np.ones((300_000,), np.float32)          # 1.2 MB closed over
    f = jax.jit(lambda p, b: b.astype(np.float32).sum()
                * jnp.asarray(weights).sum() * p)
    sig, findings = sig_and_findings(ProgramSpec('step', f, (P, B4)))
    assert rules_of(findings) == {'const-budget'}
    assert sig['const_bytes'] >= 1_200_000
    # an explicit budget accepts it (the vft-programs suppression shape)
    _, findings = sig_and_findings(
        ProgramSpec('step', f, (P, B4), const_budget=2 << 20))
    assert findings == []


def test_spec_ok_suppression_mirrors_vft_lint():
    donated = jax.jit(lambda p, b: b.astype(np.float32).sum() * p,
                      donate_argnums=(1,))
    _, findings = sig_and_findings(ProgramSpec(
        'step', donated, (P, B4),
        ok={'donation': 'toy: donation is the point'}))
    assert findings == []


def test_mesh_width_2_signature_records_partitions():
    from video_features_tpu.parallel.mesh import batch_sharding, replicated
    mesh = make_mesh(n_devices=2, time_parallel=1)
    f = jax.jit(lambda p, b: b.astype(np.float32).sum(axis=1) * p)
    pp = jax.ShapeDtypeStruct((), np.float32, sharding=replicated(mesh))
    bb = jax.ShapeDtypeStruct((4, 8), np.uint8,
                              sharding=batch_sharding(mesh))
    sig, findings = sig_and_findings(ProgramSpec('step', f, (pp, bb)),
                                     width=2, mesh=mesh)
    assert findings == []
    assert sig['num_partitions'] == 2


# -- lock semantics on a real family -----------------------------------------

@pytest.fixture(scope='module')
def r21d_live():
    """One r21d build + both mesh-width lowerings, shared by the lock
    tests (the build is the expensive part)."""
    live, findings = collect(('r21d',), (1, 2))
    assert findings == []
    return live


def test_write_lock_is_idempotent(r21d_live, tmp_path):
    lock = tmp_path / 'lock.json'
    write_lock(lock, r21d_live)
    first = lock.read_text()
    write_lock(lock, r21d_live)
    assert lock.read_text() == first
    doc = json.loads(first)
    assert set(doc['families']) == {'r21d'}
    assert set(doc['families']['r21d']) == {'mesh1', 'mesh2'}


def test_clean_diff_against_own_lock(r21d_live, tmp_path):
    lock = tmp_path / 'lock.json'
    write_lock(lock, r21d_live)
    assert diff_lock(r21d_live, load_lock(lock), ('r21d',)) == []


def test_mesh_width_subset_repin_keeps_other_widths(r21d_live, tmp_path):
    """A --mesh-widths subset re-pin must merge, not drop, the family's
    other widths' pinned signatures — and a subset CHECK must not
    report the unchecked widths as stale."""
    lock = tmp_path / 'lock.json'
    write_lock(lock, r21d_live)
    only_m1 = {'r21d': {'mesh1': r21d_live['r21d']['mesh1']}}
    write_lock(lock, only_m1)
    doc = json.loads(lock.read_text())
    assert set(doc['families']['r21d']) == {'mesh1', 'mesh2'}
    assert diff_lock(r21d_live, load_lock(lock), ('r21d',)) == []
    # width-subset diff: live has only mesh1, lock has both — clean
    assert diff_lock(only_m1, load_lock(lock), ('r21d',),
                     widths=(1,)) == []


def test_injected_dtype_drift_is_reported(r21d_live, tmp_path):
    lock = tmp_path / 'lock.json'
    write_lock(lock, r21d_live)
    doc = json.loads(lock.read_text())
    step = doc['families']['r21d']['mesh1']['programs']['step']
    step['batch']['dtype'] = 'float64'               # the injected drift
    lock.write_text(json.dumps(doc))
    findings = diff_lock(r21d_live, load_lock(lock), ('r21d',))
    assert len(findings) == 1
    f = findings[0]
    assert (f.rule, f.family, f.mesh, f.program) \
        == ('lock-drift', 'r21d', 1, 'step')
    assert 'batch' in f.message and 'float64' in f.message


def test_unknown_family_in_lock_is_reported(r21d_live, tmp_path):
    lock = tmp_path / 'lock.json'
    write_lock(lock, r21d_live)
    doc = json.loads(lock.read_text())
    doc['families']['betamax'] = {'mesh1': {'programs': {}}}
    lock.write_text(json.dumps(doc))
    findings = diff_lock(r21d_live, load_lock(lock), ('r21d',))
    assert len(findings) == 1
    assert findings[0].family == 'betamax'
    assert 'unknown family' in findings[0].message


def test_missing_and_stale_programs_are_both_drift(r21d_live, tmp_path):
    lock = tmp_path / 'lock.json'
    write_lock(lock, r21d_live)
    doc = json.loads(lock.read_text())
    progs = doc['families']['r21d']['mesh1']['programs']
    progs['ghost'] = dict(progs['step'])             # pinned, never lowered
    lock.write_text(json.dumps(doc))
    findings = diff_lock(r21d_live, load_lock(lock), ('r21d',))
    assert [f.program for f in findings] == ['ghost']
    assert 'stale' in findings[0].message
    # and the reverse: a live program the lock has never seen
    doc['families']['r21d']['mesh1']['programs'] = {}
    lock.write_text(json.dumps(doc))
    findings = diff_lock(r21d_live, load_lock(lock), ('r21d',))
    assert any('new program not in the lock' in f.message
               for f in findings)


def test_full_scope_repin_prunes_stale_lock_entries(r21d_live, tmp_path):
    """The bare --write-lock must make the 'unknown family' finding's
    own remediation advice work: stale families (and stale width keys)
    are pruned on a full-scope re-pin, kept on subset re-pins."""
    lock = tmp_path / 'lock.json'
    write_lock(lock, {'betamax': {'mesh9': {'programs': {}}}})
    write_lock(lock, r21d_live)                       # subset: kept
    assert 'betamax' in json.loads(lock.read_text())['families']
    write_lock(lock, r21d_live, prune_families=True,
               replace_widths=True)                   # full scope
    assert set(json.loads(lock.read_text())['families']) == {'r21d'}


def test_const_bytes_recorded_at_every_width(r21d_live):
    """Width-conditional signature fields would make a --mesh-widths
    subset run drift against a full-width lock (review regression)."""
    for mesh in ('mesh1', 'mesh2'):
        assert 'const_bytes' in \
            r21d_live['r21d'][mesh]['programs']['step']


def test_unpinned_family_is_drift(r21d_live):
    findings = diff_lock(r21d_live, {'families': {}}, ('r21d',))
    assert len(findings) == 1 and 'not in the lock' in findings[0].message


# -- CLI exit codes (the CI contract) ----------------------------------------

def test_cli_exit_0_clean_and_2_on_drift(tmp_path, capsys):
    lock = tmp_path / 'lock.json'
    assert main(['--families', 'resnet', '--write-lock',
                 '--lock', str(lock)]) == EXIT_CLEAN
    assert main(['--families', 'resnet',
                 '--lock', str(lock)]) == EXIT_CLEAN
    doc = json.loads(lock.read_text())
    step = doc['families']['resnet']['mesh1']['programs']['step']
    step['params']['float32']['arrays'] += 1         # injected census drift
    lock.write_text(json.dumps(doc))
    assert main(['--families', 'resnet',
                 '--lock', str(lock)]) == EXIT_FINDINGS
    out = capsys.readouterr().out
    assert 'params drifted' in out


# -- the live-tree gate vs the SHIPPED lock ----------------------------------

def test_shipped_lock_covers_all_families_at_both_widths():
    doc = load_lock(default_lock_path())
    assert set(doc['families']) == set(FAMILIES)
    for family, entry in doc['families'].items():
        assert set(entry) == {'mesh1', 'mesh2'}, family
        for mesh in entry.values():
            assert mesh['programs'], family


def test_live_tree_clean_fast_families():
    """Tier-1 slice of the CI programs-check gate: the two cheapest
    builds against the shipped lock (the slow lane + CI run all 8)."""
    assert main(['--families', 'r21d,resnet']) == EXIT_CLEAN


@pytest.mark.slow
def test_live_tree_clean_all_families():
    assert main([]) == EXIT_CLEAN


def test_family_lock_hashes_reads_shipped_lock():
    hashes = family_lock_hashes('r21d')
    assert set(hashes) == {'mesh1', 'mesh2'}
    assert set(hashes['mesh1']) == {'step'}
    assert len(hashes['mesh1']['step']) == 64
    assert family_lock_hashes('not-a-family') == {}


def test_manifest_records_programs_lock(tmp_path):
    """configure_obs attaches the family's pinned hashes; the manifest
    document carries them under the 'programs_lock' key."""
    from video_features_tpu.config import load_config
    from video_features_tpu.registry import create_extractor
    args = load_config('r21d', overrides={
        'device': 'cpu', 'video_paths': ['x.mp4'],
        'allow_random_weights': True, 'compilation_cache_dir': None,
        'manifest_out': str(tmp_path / 'manifest.json')})
    ex = create_extractor(args)
    doc = ex.manifest.document()
    assert doc['programs_lock'] == {'r21d': family_lock_hashes('r21d')}


# -- float32 boundary parity (the no-f64 satellite) --------------------------

def test_vggish_float32_pin_matches_jax_implicit_downcast():
    """The explicit host-side ``astype(np.float32)`` at the vggish
    device boundary must be byte-identical to the implicit float64
    canonicalization jax used to apply at device_put (x64 disabled) —
    same double→float rounding, so the pin changes nothing."""
    rng = np.random.default_rng(0)
    examples = rng.standard_normal((5, 96, 64)) * 4 - 2   # float64 DSP out
    explicit = examples.astype(np.float32)
    implicit = np.asarray(jax.device_put(examples))
    assert implicit.dtype == np.float32
    np.testing.assert_array_equal(explicit, implicit)


def test_host_transforms_preserve_uint8():
    from video_features_tpu.ops.host_transforms import (
        center_crop_host, frames_match_device_contract, resize_pil,
    )
    frame = np.random.default_rng(1).integers(
        0, 255, (120, 160, 3), dtype=np.uint8)
    for out in (resize_pil(frame, 64), center_crop_host(frame, 96),
                resize_pil(frame, 64, interpolation='bicubic')):
        assert frames_match_device_contract(out), out.dtype
    assert not frames_match_device_contract(frame.astype(np.float64))


class FloatLeakRecipe:
    """Module-level (spawn unpickles by reference): yields one float64
    window — numpy default-dtype math leaking through a transform."""

    def open(self, path):
        def windows():
            yield np.zeros((8, 8, 3), np.float64), 0
        return {}, windows()


def test_farm_worker_rejects_float_windows(tmp_path, caplog):
    """A recipe leaking float windows fails ITS video with the dtype
    contract named (worker 'err' path) — shipped bytes must always
    agree with the in-process decode replay, and jax's silent f64
    downcast would have masked the disagreement."""
    import logging

    from video_features_tpu.farm import DecodeFarm
    from video_features_tpu.parallel.packing import FLUSH, NUDGE, VideoTask

    task = VideoTask(str(tmp_path / 'leak.bin'))
    farm = DecodeFarm(FloatLeakRecipe(), workers=1, ring_bytes=1 << 20)
    with caplog.at_level(logging.WARNING, logger='video_features_tpu'):
        for item in farm.stream(iter([task]), lambda t: True):
            if item is FLUSH or item is NUDGE:
                continue
    assert task.failed
    assert farm.stats()['videos_failed'] == 1
    assert 'must be uint8' in caplog.text
