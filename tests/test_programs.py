"""vft-programs (video_features_tpu/analysis/programs.py): the program
contract checker itself.

Three layers, mirroring tests/test_analysis.py:

  * toy jitted functions with PLANTED violations, one per rule — the
    signature extraction + rule pass must catch each (and must NOT fire
    on the clean variant);
  * lock semantics on a real family (r21d — the cheapest build):
    ``--write-lock`` idempotence, injected dtype drift → exit 2, stale /
    unknown lock entries reported;
  * the live-tree gate: the cheap families checked against the SHIPPED
    ``PROGRAMS.lock.json`` in tier-1, all eight in the slow lane — the
    same gate CI's ``programs-check`` job enforces.

Plus the float32-boundary parity assertions the no-f64 rule leans on
(vggish's explicit host-side narrowing must equal jax's old implicit
device_put downcast; host transforms must preserve uint8).
"""
import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from video_features_tpu.analysis.core import EXIT_CLEAN, EXIT_FINDINGS
from video_features_tpu.analysis.programs import (
    ALL_PINNED, FAMILIES, ProgramSpec, build_family, check_program, collect,
    default_lock_path, diff_lock, family_lock_hashes, lane_families,
    load_lock, main, mesh_key, parse_mesh_key, program_signature,
    write_lock,
)
from video_features_tpu.parallel.mesh import make_mesh
from video_features_tpu.registry import BF16_FEATURES, INT8_FEATURES


def sig_and_findings(spec, family='toy', width=1, mesh=None):
    sig = program_signature(spec)
    return sig, check_program(spec, sig, family, width, mesh)


def rules_of(findings):
    return {f.rule for f in findings}


P = jax.ShapeDtypeStruct((), np.float32)
B4 = jax.ShapeDtypeStruct((4, 8), np.uint8)


# -- per-rule toys -----------------------------------------------------------

def test_clean_toy_has_no_findings_and_full_signature():
    f = jax.jit(lambda p, b: b.astype(np.float32).sum(axis=1) * p)
    sig, findings = sig_and_findings(ProgramSpec('step', f, (P, B4)))
    assert findings == []
    assert sig['batch'] == {'shape': [4, 8], 'dtype': 'uint8'}
    assert sig['out'] == [{'shape': [4], 'dtype': 'float32'}]
    assert sig['batch_donated'] is False
    assert sig['const_bytes'] == 0
    assert sig['num_partitions'] == 1
    assert len(sig['stablehlo_sha256']) == 64


def test_no_f64_rule_catches_planted_promotion():
    from jax.experimental import enable_x64
    with enable_x64():
        f = jax.jit(lambda p, b: b.astype(np.float64).sum() * p)
        _, findings = sig_and_findings(ProgramSpec('step', f, (P, B4)))
    assert rules_of(findings) == {'no-f64'}


def test_no_weak_type_rule_catches_scalar_only_epilogue():
    f = jax.jit(lambda p, b: jnp.sin(1.0))
    _, findings = sig_and_findings(ProgramSpec('step', f, (P, B4)))
    assert rules_of(findings) == {'no-weak-type'}


def test_no_host_callback_rule():
    def cb(x):
        return np.asarray(x)

    f = jax.jit(lambda p, b: jax.pure_callback(
        cb, jax.ShapeDtypeStruct(b.shape, np.float32), b))
    _, findings = sig_and_findings(ProgramSpec('step', f, (P, B4)))
    assert 'no-host-callback' in rules_of(findings)


def test_donation_rule_both_directions():
    donated = jax.jit(lambda p, b: b.astype(np.float32).sum() * p,
                      donate_argnums=(1,))
    plain = jax.jit(lambda p, b: b.astype(np.float32).sum() * p)
    # program donates, spec says it must not
    _, findings = sig_and_findings(ProgramSpec('step', donated, (P, B4)))
    assert rules_of(findings) == {'donation'}
    # spec expects donation, program dropped it
    _, findings = sig_and_findings(
        ProgramSpec('step', plain, (P, B4), donate_batch=True))
    assert rules_of(findings) == {'donation'}
    # declared + lowered agree
    sig, findings = sig_and_findings(
        ProgramSpec('step', donated, (P, B4), donate_batch=True))
    assert findings == [] and sig['batch_donated'] is True


def test_shardable_rule_names_indivisible_batch():
    f = jax.jit(lambda p, b: b.astype(np.float32).sum(axis=1) * p)
    odd = jax.ShapeDtypeStruct((3, 8), np.uint8)
    mesh = make_mesh(n_devices=2, time_parallel=1)
    _, findings = sig_and_findings(ProgramSpec('step', f, (P, odd)),
                                   width=2, mesh=mesh)
    assert rules_of(findings) == {'shardable'}
    assert 'cannot shard over 2' in findings[0].message


def test_const_budget_rule_catches_closure_captured_weights():
    weights = np.ones((300_000,), np.float32)          # 1.2 MB closed over
    f = jax.jit(lambda p, b: b.astype(np.float32).sum()
                * jnp.asarray(weights).sum() * p)
    sig, findings = sig_and_findings(ProgramSpec('step', f, (P, B4)))
    assert rules_of(findings) == {'const-budget'}
    assert sig['const_bytes'] >= 1_200_000
    # an explicit budget accepts it (the vft-programs suppression shape)
    _, findings = sig_and_findings(
        ProgramSpec('step', f, (P, B4), const_budget=2 << 20))
    assert findings == []


def test_spec_ok_suppression_mirrors_vft_lint():
    donated = jax.jit(lambda p, b: b.astype(np.float32).sum() * p,
                      donate_argnums=(1,))
    _, findings = sig_and_findings(ProgramSpec(
        'step', donated, (P, B4),
        ok={'donation': 'toy: donation is the point'}))
    assert findings == []


def test_mesh_width_2_signature_records_partitions():
    from video_features_tpu.parallel.mesh import batch_sharding, replicated
    mesh = make_mesh(n_devices=2, time_parallel=1)
    f = jax.jit(lambda p, b: b.astype(np.float32).sum(axis=1) * p)
    pp = jax.ShapeDtypeStruct((), np.float32, sharding=replicated(mesh))
    bb = jax.ShapeDtypeStruct((4, 8), np.uint8,
                              sharding=batch_sharding(mesh))
    sig, findings = sig_and_findings(ProgramSpec('step', f, (pp, bb)),
                                     width=2, mesh=mesh)
    assert findings == []
    assert sig['num_partitions'] == 2


# -- lock semantics on a real family -----------------------------------------

@pytest.fixture(scope='module')
def r21d_live():
    """One r21d build + both mesh-width lowerings, shared by the lock
    tests (the build is the expensive part). float32 lane only: the
    width/merge semantics under test are lane-independent, and the bf16
    lane's own semantics have their targeted tests below — one build
    here instead of two keeps the module inside the tier-1 budget."""
    live, findings = collect(('r21d',), (1, 2), lanes=('float32',))
    assert findings == []
    return live


def test_write_lock_is_idempotent(r21d_live, tmp_path):
    lock = tmp_path / 'lock.json'
    write_lock(lock, r21d_live)
    first = lock.read_text()
    write_lock(lock, r21d_live)
    assert lock.read_text() == first
    doc = json.loads(first)
    assert set(doc['families']) == {'r21d'}
    assert set(doc['families']['r21d']) == {'mesh1', 'mesh2'}


def test_clean_diff_against_own_lock(r21d_live, tmp_path):
    lock = tmp_path / 'lock.json'
    write_lock(lock, r21d_live)
    assert diff_lock(r21d_live, load_lock(lock), ('r21d',)) == []


def test_mesh_width_subset_repin_keeps_other_widths(r21d_live, tmp_path):
    """A --mesh-widths subset re-pin must merge, not drop, the family's
    other widths' pinned signatures — and a subset CHECK must not
    report the unchecked widths as stale."""
    lock = tmp_path / 'lock.json'
    write_lock(lock, r21d_live)
    only_m1 = {'r21d': {'mesh1': r21d_live['r21d']['mesh1']}}
    write_lock(lock, only_m1)
    doc = json.loads(lock.read_text())
    assert set(doc['families']['r21d']) == {'mesh1', 'mesh2'}
    assert diff_lock(r21d_live, load_lock(lock), ('r21d',)) == []
    # width-subset diff: live has only mesh1, lock has both — clean
    assert diff_lock(only_m1, load_lock(lock), ('r21d',),
                     widths=(1,)) == []


def test_injected_dtype_drift_is_reported(r21d_live, tmp_path):
    lock = tmp_path / 'lock.json'
    write_lock(lock, r21d_live)
    doc = json.loads(lock.read_text())
    step = doc['families']['r21d']['mesh1']['programs']['step']
    step['batch']['dtype'] = 'float64'               # the injected drift
    lock.write_text(json.dumps(doc))
    findings = diff_lock(r21d_live, load_lock(lock), ('r21d',))
    assert len(findings) == 1
    f = findings[0]
    assert (f.rule, f.family, f.mesh, f.program) \
        == ('lock-drift', 'r21d', 1, 'step')
    assert 'batch' in f.message and 'float64' in f.message


def test_unknown_family_in_lock_is_reported(r21d_live, tmp_path):
    lock = tmp_path / 'lock.json'
    write_lock(lock, r21d_live)
    doc = json.loads(lock.read_text())
    doc['families']['betamax'] = {'mesh1': {'programs': {}}}
    lock.write_text(json.dumps(doc))
    findings = diff_lock(r21d_live, load_lock(lock), ('r21d',))
    assert len(findings) == 1
    assert findings[0].family == 'betamax'
    assert 'unknown family' in findings[0].message


def test_missing_and_stale_programs_are_both_drift(r21d_live, tmp_path):
    lock = tmp_path / 'lock.json'
    write_lock(lock, r21d_live)
    doc = json.loads(lock.read_text())
    progs = doc['families']['r21d']['mesh1']['programs']
    progs['ghost'] = dict(progs['step'])             # pinned, never lowered
    lock.write_text(json.dumps(doc))
    findings = diff_lock(r21d_live, load_lock(lock), ('r21d',))
    assert [f.program for f in findings] == ['ghost']
    assert 'stale' in findings[0].message
    # and the reverse: a live program the lock has never seen
    doc['families']['r21d']['mesh1']['programs'] = {}
    lock.write_text(json.dumps(doc))
    findings = diff_lock(r21d_live, load_lock(lock), ('r21d',))
    assert any('new program not in the lock' in f.message
               for f in findings)


def test_full_scope_repin_prunes_stale_lock_entries(r21d_live, tmp_path):
    """The bare --write-lock must make the 'unknown family' finding's
    own remediation advice work: stale families (and stale width keys)
    are pruned on a full-scope re-pin, kept on subset re-pins."""
    lock = tmp_path / 'lock.json'
    write_lock(lock, {'betamax': {'mesh9': {'programs': {}}}})
    write_lock(lock, r21d_live)                       # subset: kept
    assert 'betamax' in json.loads(lock.read_text())['families']
    write_lock(lock, r21d_live, prune_families=True,
               replace_widths=True)                   # full scope
    assert set(json.loads(lock.read_text())['families']) == {'r21d'}


def test_const_bytes_recorded_at_every_width(r21d_live):
    """Width-conditional signature fields would make a --mesh-widths
    subset run drift against a full-width lock (review regression)."""
    for mesh in ('mesh1', 'mesh2'):
        assert 'const_bytes' in \
            r21d_live['r21d'][mesh]['programs']['step']


def test_unpinned_family_is_drift(r21d_live):
    findings = diff_lock(r21d_live, {'families': {}}, ('r21d',))
    assert len(findings) == 1 and 'not in the lock' in findings[0].message


# -- CLI exit codes (the CI contract) ----------------------------------------

def test_cli_exit_0_clean_and_2_on_drift(tmp_path, capsys):
    # float32 lane only: each main() builds once per lane, and this test
    # runs three mains — the lane-aware CLI/diff semantics have their
    # own (single-build) coverage above, so doubling every build here
    # would buy nothing but tier-1 wall clock
    lane = ['--lanes', 'float32']
    lock = tmp_path / 'lock.json'
    assert main(['--families', 'resnet', '--write-lock',
                 '--lock', str(lock)] + lane) == EXIT_CLEAN
    assert main(['--families', 'resnet',
                 '--lock', str(lock)] + lane) == EXIT_CLEAN
    doc = json.loads(lock.read_text())
    step = doc['families']['resnet']['mesh1']['programs']['step']
    step['params']['float32']['arrays'] += 1         # injected census drift
    lock.write_text(json.dumps(doc))
    assert main(['--families', 'resnet',
                 '--lock', str(lock)] + lane) == EXIT_FINDINGS
    out = capsys.readouterr().out
    assert 'params drifted' in out


# -- the live-tree gate vs the SHIPPED lock ----------------------------------

def test_shipped_lock_covers_all_families_at_both_widths():
    """Every pinned family — the model families plus the extra shipped
    programs (the feature index's query program) — pins both mesh widths
    on the float32 lane, and every bf16-accepting family
    (registry.BF16_FEATURES) ADDITIONALLY pins both widths of its
    mesh<n>@bfloat16 fast-lane variants — a refusing family (i3d, raft)
    must have none."""
    doc = load_lock(default_lock_path())
    assert set(doc['families']) == set(ALL_PINNED)
    for family, entry in doc['families'].items():
        want = {'mesh1', 'mesh2'}
        if family in BF16_FEATURES:
            want |= {'mesh1@bfloat16', 'mesh2@bfloat16'}
        if family in INT8_FEATURES:
            want |= {'mesh1@int8', 'mesh2@int8'}
        assert set(entry) == want, family
        for mesh in entry.values():
            assert mesh['programs'], family


def test_shipped_bf16_variants_census_is_pure_bf16():
    """The lane's load-bearing acceptance: the committed lock's
    compute_dtype=bfloat16 variants carry ZERO fp32 (or fp64) params —
    proof the transplant-time cast reached every tensor (fp32 lives
    only in activation islands, which a params census never sees)."""
    doc = load_lock(default_lock_path())
    checked = 0
    for family in sorted(BF16_FEATURES):
        for key, entry in doc['families'][family].items():
            if '@bfloat16' not in key:
                continue
            for name, sig in entry['programs'].items():
                census = sig['params']
                assert set(census) == {'bfloat16'}, (family, key, name,
                                                    census)
                assert census['bfloat16']['arrays'] > 0
                checked += 1
    assert checked >= 2 * len(BF16_FEATURES)   # both widths per family


def test_shipped_int8_variants_census_is_int8_majority():
    """The int8 lane's load-bearing acceptance against the committed
    lock: every compute_dtype=int8 variant carries int8 params and its
    DECLARED fp32 minority (biases, norm params, per-channel scales)
    stays strictly under the int8 payload bytes — proof the per-channel
    weight quantization reached the conv/linear bulk of every accepting
    family (CLIP's fused in_proj_weight included, which alone would
    flip the byte majority if missed)."""
    doc = load_lock(default_lock_path())
    checked = 0
    for family in sorted(INT8_FEATURES):
        for key, entry in doc['families'][family].items():
            if '@int8' not in key:
                continue
            for name, sig in entry['programs'].items():
                census = sig['params']
                assert 'int8' in census, (family, key, name, census)
                assert census['int8']['arrays'] > 0
                assert 'float64' not in census, (family, key, name)
                f32 = census.get('float32', {}).get('bytes', 0)
                assert f32 < census['int8']['bytes'], (family, key, name,
                                                       census)
                checked += 1
    assert checked >= 2 * len(INT8_FEATURES)   # both widths per family


def test_lane_helpers_roundtrip():
    assert mesh_key(1, 'float32') == 'mesh1'          # pre-lane keys hold
    assert mesh_key(2, 'bfloat16') == 'mesh2@bfloat16'
    assert mesh_key(2, 'int8') == 'mesh2@int8'
    assert parse_mesh_key('mesh1') == (1, 'float32')
    assert parse_mesh_key('mesh2@bfloat16') == (2, 'bfloat16')
    assert parse_mesh_key('mesh1@int8') == (1, 'int8')
    assert lane_families('float32', FAMILIES) == FAMILIES
    assert set(lane_families('bfloat16', FAMILIES)) == BF16_FEATURES
    assert set(lane_families('int8', FAMILIES)) == INT8_FEATURES


def test_bf16_census_rule_catches_fp32_survivor():
    """A bf16-lane program whose params census still shows float32
    arrays must trip 'bf16-census' — and the same signature on the
    float32 lane must not (fp32 params are that lane's contract)."""
    f = jax.jit(lambda p, b: (b.astype(jnp.bfloat16).sum(axis=1)
                              * p).astype(np.float32))
    spec = ProgramSpec('step', f, (P, B4))      # P is a float32 param
    sig = program_signature(spec)
    bf16_findings = check_program(spec, sig, 'toy', 1, None,
                                  lane='bfloat16')
    assert rules_of(bf16_findings) == {'bf16-census'}
    assert 'float32' in bf16_findings[0].message
    assert '@bfloat16' in bf16_findings[0].render()
    assert check_program(spec, sig, 'toy', 1, None,
                         lane='float32') == []


def test_int8_census_rule_catches_unquantized_params():
    """An int8-lane program must carry int8 params OUTWEIGHING its fp32
    minority: a plain-fp32 toy trips 'int8-census' (nothing quantized),
    a quantized toy with a small fp32 scale rides clean — and the same
    fp32 signature on the float32 lane must not fire (fp32 params are
    that lane's contract)."""
    w8 = jax.ShapeDtypeStruct((64, 8), np.int8)     # 512 int8 bytes
    sc = jax.ShapeDtypeStruct((1, 8), np.float32)   # 32 fp32 bytes
    fq = jax.jit(lambda q, s, b: (b.astype(np.float32)
                                  @ (q.astype(np.float32) * s)))
    b64 = jax.ShapeDtypeStruct((4, 64), np.uint8)
    spec_ok = ProgramSpec('step', fq, (w8, sc, b64))
    sig_ok = program_signature(spec_ok)
    assert check_program(spec_ok, sig_ok, 'toy', 1, None,
                         lane='int8') == []
    # unquantized: fp32-only params on the int8 lane
    f = jax.jit(lambda p, b: b.astype(np.float32).sum(axis=1) * p)
    spec = ProgramSpec('step', f, (P, B4))
    sig = program_signature(spec)
    findings = check_program(spec, sig, 'toy', 1, None, lane='int8')
    assert rules_of(findings) == {'int8-census'}
    assert '@int8' in findings[0].render()
    assert check_program(spec, sig, 'toy', 1, None, lane='float32') == []


def test_bf16_lane_collect_and_lock_roundtrip(tmp_path):
    """One REAL bf16-lane build (vggish — the cheapest family): collect
    places it under mesh<n>@bfloat16, write-lock/diff round-trips clean,
    and a census-drift plant in the bf16 variant is reported with the
    lane named."""
    live, findings = collect(('vggish',), (1,), lanes=('bfloat16',))
    assert findings == []
    assert set(live['vggish']) == {'mesh1@bfloat16'}
    sig = live['vggish']['mesh1@bfloat16']['programs']['step']
    assert set(sig['params']) == {'bfloat16'}
    assert sig['batch']['dtype'] == 'bfloat16'   # halved H2D at the edge
    lock = tmp_path / 'lock.json'
    write_lock(lock, live)
    assert diff_lock(live, load_lock(lock), ('vggish',),
                     widths=(1,)) == []
    doc = json.loads(lock.read_text())
    doc['families']['vggish']['mesh1@bfloat16']['programs']['step'][
        'params'] = {'float32': {'arrays': 1, 'bytes': 4}}
    lock.write_text(json.dumps(doc))
    findings = diff_lock(live, load_lock(lock), ('vggish',), widths=(1,))
    assert len(findings) == 1
    assert findings[0].lane == 'bfloat16'
    assert 'params drifted' in findings[0].message


def test_live_tree_clean_fast_families():
    """Tier-1 slice of the CI programs-check gate: the two cheapest
    builds against the shipped lock (the slow lane + CI run all 8,
    both lanes). resnet runs BOTH lanes (the bf16 variants gate in
    tier-1 too); r21d pins float32 only here — its bf16 build would be
    a third full build and the CI job covers it."""
    assert main(['--families', 'resnet']) == EXIT_CLEAN
    assert main(['--families', 'r21d', '--lanes', 'float32']) == EXIT_CLEAN


@pytest.mark.slow
def test_live_tree_clean_all_families():
    assert main([]) == EXIT_CLEAN


def test_family_lock_hashes_reads_shipped_lock():
    hashes = family_lock_hashes('r21d')
    # a run manifest names its lane's pinned program: the bf16 variants
    # ride the same mapping under their mesh<n>@bfloat16 keys
    assert set(hashes) == {'mesh1', 'mesh2',
                           'mesh1@bfloat16', 'mesh2@bfloat16'}
    assert set(hashes['mesh1']) == {'step'}
    assert len(hashes['mesh1']['step']) == 64
    assert len(hashes['mesh1@bfloat16']['step']) == 64
    assert hashes['mesh1@bfloat16']['step'] != hashes['mesh1']['step']
    assert family_lock_hashes('not-a-family') == {}


def test_manifest_records_programs_lock(tmp_path):
    """configure_obs attaches the family's pinned hashes; the manifest
    document carries them under the 'programs_lock' key."""
    from video_features_tpu.config import load_config
    from video_features_tpu.registry import create_extractor
    args = load_config('r21d', overrides={
        'device': 'cpu', 'video_paths': ['x.mp4'],
        'allow_random_weights': True, 'compilation_cache_dir': None,
        'manifest_out': str(tmp_path / 'manifest.json')})
    ex = create_extractor(args)
    doc = ex.manifest.document()
    assert doc['programs_lock'] == {'r21d': family_lock_hashes('r21d')}


# -- float32 boundary parity (the no-f64 satellite) --------------------------

def test_vggish_float32_pin_matches_jax_implicit_downcast():
    """The explicit host-side ``astype(np.float32)`` at the vggish
    device boundary must be byte-identical to the implicit float64
    canonicalization jax used to apply at device_put (x64 disabled) —
    same double→float rounding, so the pin changes nothing."""
    rng = np.random.default_rng(0)
    examples = rng.standard_normal((5, 96, 64)) * 4 - 2   # float64 DSP out
    explicit = examples.astype(np.float32)
    implicit = np.asarray(jax.device_put(examples))
    assert implicit.dtype == np.float32
    np.testing.assert_array_equal(explicit, implicit)


def test_host_transforms_preserve_uint8():
    from video_features_tpu.ops.host_transforms import (
        center_crop_host, frames_match_device_contract, resize_pil,
    )
    frame = np.random.default_rng(1).integers(
        0, 255, (120, 160, 3), dtype=np.uint8)
    for out in (resize_pil(frame, 64), center_crop_host(frame, 96),
                resize_pil(frame, 64, interpolation='bicubic')):
        assert frames_match_device_contract(out), out.dtype
    assert not frames_match_device_contract(frame.astype(np.float64))


class FloatLeakRecipe:
    """Module-level (spawn unpickles by reference): yields one float64
    window — numpy default-dtype math leaking through a transform."""

    def open(self, path):
        def windows():
            yield np.zeros((8, 8, 3), np.float64), 0
        return {}, windows()


def test_farm_worker_rejects_float_windows(tmp_path, caplog):
    """A recipe leaking float windows fails ITS video with the dtype
    contract named (worker 'err' path) — shipped bytes must always
    agree with the in-process decode replay, and jax's silent f64
    downcast would have masked the disagreement."""
    import logging

    from video_features_tpu.farm import DecodeFarm
    from video_features_tpu.parallel.packing import FLUSH, NUDGE, VideoTask

    task = VideoTask(str(tmp_path / 'leak.bin'))
    farm = DecodeFarm(FloatLeakRecipe(), workers=1, ring_bytes=1 << 20)
    with caplog.at_level(logging.WARNING, logger='video_features_tpu'):
        for item in farm.stream(iter([task]), lambda t: True):
            if item is FLUSH or item is NUDGE:
                continue
    assert task.failed
    assert farm.stats()['videos_failed'] == 1
    assert 'must be uint8' in caplog.text
