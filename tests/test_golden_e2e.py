"""End-to-end golden parity: whole-file features vs the reference pipeline.

The north-star metric (BASELINE.json) is feature L2 vs the reference
implementation at the `.npy` level — decode, resize, windowing, RAFT, both
I3D towers, concat, serialization all in the loop. These tests record a
golden from the reference-equivalent torch pipeline (tests/reference_
pipeline.py — the reference's own nets + transforms, composed exactly like
extract_i3d.py) and run OUR extractor CLI-style on the same video with the
same weights saved as real .pt checkpoints.

Weights are seeded-random (the reference's pretrained blobs are absent in
this environment — reference/.MISSING_LARGE_BLOBS); with real checkpoints
on disk the same harness measures real-weight parity (tools/
measure_parity.py --checkpoints writes PARITY.md rows from them).
"""
import numpy as np
import pytest

from video_features_tpu.config import load_config
from video_features_tpu.registry import create_extractor

pytestmark = pytest.mark.slow

REL_L2_TARGET = 1e-3  # BASELINE.json parity bar


def _rel_l2(a, b):
    return np.linalg.norm(a - b) / max(np.linalg.norm(b), 1e-12)


@pytest.fixture(scope='module')
def golden(reference_repo, video_33, tmp_path_factory):
    """Reference-pipeline outputs + the .pt checkpoints that produced them."""
    from tests.reference_pipeline import (
        build_reference_nets, run_reference_i3d, save_state_dicts,
    )
    nets = build_reference_nets(seed=0)
    ckpts = save_state_dicts(nets, tmp_path_factory.mktemp('ckpts'))
    feats = run_reference_i3d(video_33, nets, stack_size=16)
    return {'feats': feats, 'ckpts': ckpts}


def test_i3d_two_stream_e2e_golden(golden, video_33, tmp_path):
    """Flagship: the (T, 2048) rgb∥flow concat written to .npy matches the
    reference pipeline end-to-end at rel L2 ≤ 1e-3 (precision=highest)."""
    args = load_config('i3d', overrides={
        'video_paths': video_33,
        'device': 'cpu',
        'precision': 'highest',
        # cv2 decode pinned for golden stability; since round 5 the
        # native backend is bit-exact to cv2 anyway
        # (native/yuv2rgb_cv2_tables.h), so 'auto' would measure the same
        'decode_backend': 'cv2',
        'stack_size': 16, 'step_size': 16,
        'concat_rgb_flow': True,
        'on_extraction': 'save_numpy',
        'i3d_rgb_checkpoint_path': golden['ckpts']['rgb'],
        'i3d_flow_checkpoint_path': golden['ckpts']['flow'],
        'raft_checkpoint_path': golden['ckpts']['raft'],
        'output_path': str(tmp_path / 'out'),
        'tmp_path': str(tmp_path / 'tmp'),
    })
    ex = create_extractor(args)
    ex._extract(video_33)  # the full save path, like the CLI loop

    from video_features_tpu.utils.output import make_path
    out = np.load(make_path(args.output_path, video_33, 'rgb', '.npy'))

    ref = np.concatenate(
        [golden['feats']['rgb'], golden['feats']['flow']], axis=-1)
    assert out.shape == ref.shape == (2, 2048)
    rels = {'concat': _rel_l2(out, ref)}
    for i, stream in enumerate(('rgb', 'flow')):
        rels[stream] = _rel_l2(out[:, i * 1024:(i + 1) * 1024],
                               golden['feats'][stream])
    print(f'[golden e2e] rel L2: {rels}')
    # Every stream is held to the BASELINE.json bar with no loosening.
    # rgb: decode → resize → crop → I3D is deterministic and measures
    # ~1e-6 (any regression in the frame pipeline fails this hard).
    # flow: passes through the uint8 quantization stage (clamp ±20 →
    # round(128 + 255/40·x)); the seeded weights are shaped so the flow
    # field has realistic magnitude (see reference_pipeline.
    # build_reference_nets) and the quantized comparison measures pipeline
    # parity rather than clamp-boundary rounding artifacts. The
    # un-quantized flow path is additionally held to the strict bar
    # end-to-end by test_raft_flow_e2e_golden below.
    assert rels['rgb'] < REL_L2_TARGET, f'rgb rel L2: {rels}'
    assert rels['flow'] < REL_L2_TARGET, f'flow rel L2: {rels}'
    assert rels['concat'] < REL_L2_TARGET, f'concat rel L2: {rels}'


def test_i3d_stack64_e2e_golden(reference_repo, video_65, tmp_path):
    """Upstream-geometry flagship golden (VERDICT r4 task 8): upstream's
    documented default is 64-frame stacks (reference docs/models/
    i3d.md:15-18) while the fork's — and every other golden's — is 16.
    One stack-64 window exercises I3D's temporal pooling at the published
    depth and RAFT's 64-pair batch memory. raft_iters=8 on BOTH sides
    keeps the two-sided comparison valid while holding CPU runtime to
    slow-lane budget (the 20-iter depth is covered by the stack-16
    flagship golden above)."""
    import torch

    from tests.reference_pipeline import (
        build_reference_nets, run_reference_i3d, save_state_dicts,
    )

    torch.manual_seed(0)
    nets = build_reference_nets(seed=0)
    ckpts = save_state_dicts(nets, tmp_path / 'ckpts')
    ref = run_reference_i3d(video_65, nets, stack_size=64, raft_iters=8)

    args = load_config('i3d', overrides={
        'video_paths': video_65,
        'device': 'cpu',
        'precision': 'highest',
        'decode_backend': 'cv2',
        'stack_size': 64, 'step_size': 64, 'raft_iters': 8,
        'concat_rgb_flow': True,
        'i3d_rgb_checkpoint_path': str(ckpts['rgb']),
        'i3d_flow_checkpoint_path': str(ckpts['flow']),
        'raft_checkpoint_path': str(ckpts['raft']),
        'on_extraction': 'save_numpy',
        'output_path': str(tmp_path / 'out'),
        'tmp_path': str(tmp_path / 'tmp'),
    })
    ex = create_extractor(args)
    ex._extract(video_65)

    from video_features_tpu.utils.output import make_path
    out = np.load(make_path(args.output_path, video_65, 'rgb', '.npy'))
    refcat = np.concatenate([ref['rgb'], ref['flow']], axis=-1)
    assert out.shape == refcat.shape == (1, 2048)
    rels = {'rgb': _rel_l2(out[:, :1024], ref['rgb']),
            'flow': _rel_l2(out[:, 1024:], ref['flow']),
            'concat': _rel_l2(out, refcat)}
    print(f'[golden e2e] stack64 rel L2: {rels}')
    for k, v in rels.items():
        assert v < REL_L2_TARGET, f'{k} rel L2: {rels}'


def test_r21d_e2e_golden(reference_repo, video_33, tmp_path):
    """BASELINE config 1 end-to-end: the r21d family's whole-file (T, 512)
    output vs the reference extraction recipe (whole-video transform chain
    + form_slices windows + torchvision VideoResNet) on the same frames."""
    import torch

    from tests.reference_pipeline import (
        R21D_OVERRIDES, build_reference_r21d_net, run_reference_r21d,
    )

    net = build_reference_r21d_net(seed=0)
    ckpt = tmp_path / 'r21d_seeded.pt'
    torch.save(net.state_dict(), str(ckpt))

    ref = run_reference_r21d(video_33, net, stack_size=16, step_size=16)

    args = load_config('r21d', overrides={
        **R21D_OVERRIDES, 'video_paths': video_33,
        'checkpoint_path': str(ckpt),
        'output_path': str(tmp_path / 'out'), 'tmp_path': str(tmp_path / 't'),
    })
    ours = create_extractor(args).extract(video_33)['r21d']

    assert ours.shape == ref.shape == (2, 512)
    rel = _rel_l2(ours, ref)
    print(f'[golden e2e] r21d rel L2: {rel}')
    assert rel < REL_L2_TARGET, f'r21d e2e rel L2 {rel}'


def test_s3d_e2e_golden(reference_repo, video_33, tmp_path):
    """s3d family end-to-end: whole-file (T, 1024) output vs the reference
    recipe (no-normalization convention, torch-bilinear short-side resize,
    form_slices windows) with the reference's own S3D net."""
    import torch

    from models.s3d.s3d_src.s3d import S3D
    from tests.reference_pipeline import run_reference_s3d

    torch.manual_seed(0)
    net = S3D(num_class=400).eval()
    ckpt = tmp_path / 's3d_seeded.pt'
    torch.save(net.state_dict(), str(ckpt))

    ref = run_reference_s3d(video_33, net, stack_size=16, step_size=16)

    args = load_config('s3d', overrides={
        'video_paths': video_33, 'device': 'cpu', 'precision': 'highest',
        'decode_backend': 'cv2', 'stack_size': 16, 'step_size': 16,
        'extraction_fps': None,       # native fps both sides (no ffmpeg)
        'checkpoint_path': str(ckpt),
        'output_path': str(tmp_path / 'out'), 'tmp_path': str(tmp_path / 't'),
    })
    ours = create_extractor(args).extract(video_33)['s3d']

    assert ours.shape == ref.shape == (2, 1024)
    rel = _rel_l2(ours, ref)
    print(f'[golden e2e] s3d rel L2: {rel}')
    assert rel < REL_L2_TARGET, f's3d e2e rel L2 {rel}'


def test_clip_e2e_golden(reference_repo, video_33, tmp_path):
    """clip family end-to-end: whole-file (T, 512) output vs the reference
    transform chain + encode_image (reduced-geometry reference CLIP; the
    visual tower is the full ViT-B/32 layout)."""
    import torch

    from tests.reference_pipeline import build_reference_clip, run_reference_clip

    net = build_reference_clip(seed=0)
    ckpt = tmp_path / 'clip_seeded.pt'
    torch.save(net.state_dict(), str(ckpt))

    ref = run_reference_clip(video_33, net)

    args = load_config('clip', overrides={
        'video_paths': video_33, 'device': 'cpu', 'precision': 'highest',
        'decode_backend': 'cv2', 'batch_size': 16, 'model_name': 'custom',
        'checkpoint_path': str(ckpt),
        'output_path': str(tmp_path / 'out'), 'tmp_path': str(tmp_path / 't'),
    })
    ours = create_extractor(args).extract(video_33)['clip']

    assert ours.shape == ref.shape == (33, 512)
    rel = _rel_l2(ours, ref)
    print(f'[golden e2e] clip rel L2: {rel}')
    assert rel < REL_L2_TARGET, f'clip e2e rel L2 {rel}'


def test_resnet_e2e_golden(reference_repo, video_33, tmp_path):
    """resnet family end-to-end: whole-file (T, 2048) output vs the
    reference recipe (torchvision IMAGENET1K_V1 eval transform + the
    fc-stripped mirror net)."""
    import torch

    from tests.reference_pipeline import run_reference_resnet
    from tests.torch_mirrors import TorchResNet, randomize_bn_stats

    torch.manual_seed(0)
    net = TorchResNet('resnet50').eval()
    randomize_bn_stats(net)
    ckpt = tmp_path / 'resnet50_seeded.pt'
    torch.save(net.state_dict(), str(ckpt))

    ref = run_reference_resnet(video_33, net)

    args = load_config('resnet', overrides={
        'video_paths': video_33, 'device': 'cpu', 'precision': 'highest',
        'decode_backend': 'cv2', 'batch_size': 16, 'model_name': 'resnet50',
        'checkpoint_path': str(ckpt),
        'output_path': str(tmp_path / 'out'), 'tmp_path': str(tmp_path / 't'),
    })
    ours = create_extractor(args).extract(video_33)['resnet']

    assert ours.shape == ref.shape == (33, 2048)
    rel = _rel_l2(ours, ref)
    print(f'[golden e2e] resnet rel L2: {rel}')
    assert rel < REL_L2_TARGET, f'resnet e2e rel L2 {rel}'


@pytest.fixture(scope='module')
def real_audio_wav(sample_video, tmp_path_factory):
    """A 16 kHz 16-bit PCM wav with real audio content (shared builder:
    reference_pipeline.write_real_audio_wav). Both pipelines read this
    identical file, so the wav's provenance does not affect the parity
    measurement — only realism."""
    from tests.reference_pipeline import write_real_audio_wav

    return write_real_audio_wav(
        str(tmp_path_factory.mktemp('aud') / 'real_audio_16k.wav'),
        source_video=sample_video)


def test_vggish_e2e_golden(reference_repo, real_audio_wav, tmp_path):
    """vggish family end-to-end: whole-file (Ta, 128) output vs the
    reference's own mel_features + framing + the state-dict-matched VGG
    (reference extract_vggish.py:31-62 at the .wav entry point — the mp4
    leg needs ffmpeg, absent here; mp4→wav chain parity is covered by
    tests/test_vggish.py's backend tests)."""
    import torch

    from tests.reference_pipeline import run_reference_vggish
    from tests.torch_mirrors import TorchVGGish

    torch.manual_seed(0)
    net = TorchVGGish().eval()
    ckpt = tmp_path / 'vggish_seeded.pt'
    torch.save(net.state_dict(), str(ckpt))

    ref = run_reference_vggish(real_audio_wav, net)

    args = load_config('vggish', overrides={
        'video_paths': real_audio_wav, 'device': 'cpu',
        'precision': 'highest',
        'checkpoint_path': str(ckpt),
        'output_path': str(tmp_path / 'out'), 'tmp_path': str(tmp_path / 't'),
    })
    ours = create_extractor(args).extract(real_audio_wav)['vggish']

    assert ours.shape == ref.shape and ref.shape[1] == 128
    assert ref.shape[0] >= 5, 'fixture should yield several 0.96 s examples'
    rel = _rel_l2(ours, ref)
    print(f'[golden e2e] vggish rel L2: {rel}')
    assert rel < REL_L2_TARGET, f'vggish e2e rel L2 {rel}'


def test_s3d_e2e_golden_fps25_retimed(reference_repo, video_33, tmp_path,
                                      monkeypatch):
    """The fps-retiming path end-to-end (VERDICT r3 #6): s3d at its
    reference default extraction_fps=25 (reference configs/s3d.yml),
    through the CFR re-encode stage. BOTH sides re-encode with the native
    equivalent of the reference's ffmpeg stage
    (tests/test_native_reencode.py pins its fps-filter semantics,
    byte-determinism, and — where a binary exists — equivalence to the
    real CLI): the reference recipe decodes its own independently
    produced re-encode, our extractor runs its production retiming path.
    The ffmpeg binary is masked so hosts that have one (CI) still compare
    like against like; binary-vs-native encoder equivalence is the vs-CLI
    test's job, not this golden's."""
    import torch

    monkeypatch.setattr('video_features_tpu.io.video.which_ffmpeg',
                        lambda: '')

    from models.s3d.s3d_src.s3d import S3D
    from tests.reference_pipeline import run_reference_s3d
    from video_features_tpu.io import native

    if not native.available():
        pytest.skip('native re-encoder unavailable')

    torch.manual_seed(0)
    net = S3D(num_class=400).eval()
    ckpt = tmp_path / 's3d_seeded.pt'
    torch.save(net.state_dict(), str(ckpt))

    reenc = native.reencode_fps_native(video_33, str(tmp_path / 'ref_t'),
                                       25.0)
    ref = run_reference_s3d(reenc, net, stack_size=16, step_size=16)

    args = load_config('s3d', overrides={
        'video_paths': video_33, 'device': 'cpu', 'precision': 'highest',
        'extraction_fps': 25, 'stack_size': 16, 'step_size': 16,
        'decode_backend': 'cv2',   # decode-exact vs the reference side
        'checkpoint_path': str(ckpt),
        'output_path': str(tmp_path / 'out'), 'tmp_path': str(tmp_path / 't'),
    })
    ours = create_extractor(args).extract(video_33)['s3d']

    assert ours.shape == ref.shape and ref.shape[1] == 1024
    assert ref.shape[0] >= 1, 'retimed clip should yield a full stack'
    rel = _rel_l2(ours, ref)
    print(f'[golden e2e] s3d fps=25 retimed rel L2: {rel}')
    assert rel < REL_L2_TARGET, f's3d retimed e2e rel L2 {rel}'


def test_vggish_e2e_golden_44k(reference_repo, tmp_path):
    """vggish end-to-end on a 44.1 kHz wav — the rate every real mp4
    audio track actually has, exercising the resample stage the 16 kHz
    golden sidesteps. Reference side: literal resampy transcription →
    the reference's own mel_features → the state-dict-matched VGG. Ours:
    the production vectorized Kaiser resampler through the real extractor.
    Closes VERDICT r3 'bit-parity audio resampling' with a ≤1e-3 row."""
    import torch

    from tests.reference_pipeline import (
        run_reference_vggish, write_real_audio_wav,
    )
    from tests.torch_mirrors import TorchVGGish

    wav = write_real_audio_wav(str(tmp_path / 'real_audio_44k.wav'),
                               sr=44100)
    torch.manual_seed(0)
    net = TorchVGGish().eval()
    ckpt = tmp_path / 'vggish_seeded.pt'
    torch.save(net.state_dict(), str(ckpt))

    ref = run_reference_vggish(wav, net)

    args = load_config('vggish', overrides={
        'video_paths': wav, 'device': 'cpu',
        'precision': 'highest',
        'checkpoint_path': str(ckpt),
        'output_path': str(tmp_path / 'out'), 'tmp_path': str(tmp_path / 't'),
    })
    ours = create_extractor(args).extract(wav)['vggish']

    assert ours.shape == ref.shape and ref.shape[1] == 128
    assert ref.shape[0] >= 5, 'fixture should yield several 0.96 s examples'
    rel = _rel_l2(ours, ref)
    print(f'[golden e2e] vggish 44.1 kHz rel L2: {rel}')
    assert rel < REL_L2_TARGET, f'vggish 44.1 kHz e2e rel L2 {rel}'


def test_raft_flow_e2e_golden(reference_repo, video_33, tmp_path):
    """Un-quantized flow end-to-end at the STRICT bar: the raft family's
    whole-file (T-1, 2, H, W) output vs the reference RAFT loop on the
    same decoded frames (cv2, native resolution, /8 sintel padding)."""
    import torch

    from tests.reference_pipeline import build_reference_nets, save_state_dicts

    nets = build_reference_nets(seed=0, streams=('flow',))
    ckpts = save_state_dicts({'raft': nets['raft']}, tmp_path / 'ckpts')

    # reference side: cv2 decode → RAFT on padded consecutive pairs →
    # unpad (reference base_flow_extractor.py:76-115)
    from models.raft.raft_src.raft import InputPadder
    from tests.reference_pipeline import _read_frames_rgb
    frames = _read_frames_rgb(video_33)
    batch = torch.from_numpy(frames).permute(0, 3, 1, 2).float()
    padder = InputPadder(batch.shape)
    with torch.no_grad():
        padded = padder.pad(batch)
        flows = [padder.unpad(nets['raft'](padded[i:i + 1], padded[i + 1:i + 2]))
                 for i in range(len(frames) - 1)]
    ref = torch.cat(flows).numpy()                      # (T-1, 2, H, W)

    args = load_config('raft', overrides={
        'video_paths': video_33, 'device': 'cpu', 'precision': 'highest',
        'decode_backend': 'cv2', 'batch_size': 16,
        'checkpoint_path': ckpts['raft'],
        'output_path': str(tmp_path / 'out'), 'tmp_path': str(tmp_path / 't'),
    })
    ours = create_extractor(args).extract(video_33)['raft']

    assert ours.shape == ref.shape
    rel = _rel_l2(ours, ref)
    print(f'[golden e2e] raft flow field rel L2: {rel}')
    assert rel < REL_L2_TARGET, f'flow field rel L2 {rel}'
