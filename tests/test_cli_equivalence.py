"""Dual-API equivalence: CLI (save_numpy / save_pickle) vs the import API.

The reference's entire test harness is built on this triangle (reference
tests/utils.py:107-135): run the CLI twice (numpy + pickle actions), load
the files back, run ``extractor.extract`` directly, and require all three
to agree. The CLI here runs in-process through ``cli.main(argv)`` — the
same code path as ``python -m video_features_tpu`` — which also keeps the
jit cache warm across the three runs.
"""
from pathlib import Path

import numpy as np
import pytest

from video_features_tpu import cli
from video_features_tpu.config import load_config
from video_features_tpu.registry import create_extractor
from video_features_tpu.utils.output import load_numpy, load_pickle

pytestmark = pytest.mark.slow  # parity/e2e/sharding: full lane only


KEYS = ('resnet', 'fps', 'timestamps_ms')


def _run_cli(video, out, tmp, action):
    rc = cli.main([
        'feature_type=resnet', 'model_name=resnet18', 'device=cpu',
        'batch_size=16', f'video_paths={video}',
        f'on_extraction={action}', f'output_path={out}', f'tmp_path={tmp}',
    ])
    assert rc == 0


def _load(out_dir, stem, ext, loader):
    # make_path: non-'rgb' keys get a _<key> suffix (reference utils/utils.py:56-63)
    d = Path(out_dir) / 'resnet' / 'resnet18'
    return {k: loader(str(d / f'{stem}_{k}{ext}')) for k in KEYS}


def test_cli_numpy_pickle_import_agree(short_video, tmp_path):
    stem = Path(short_video).stem

    _run_cli(short_video, tmp_path / 'np_out', tmp_path / 'tmp', 'save_numpy')
    _run_cli(short_video, tmp_path / 'pk_out', tmp_path / 'tmp', 'save_pickle')

    from_numpy = _load(tmp_path / 'np_out', stem, '.npy', load_numpy)
    from_pickle = _load(tmp_path / 'pk_out', stem, '.pkl', load_pickle)

    args = load_config('resnet', overrides={
        'model_name': 'resnet18', 'device': 'cpu', 'batch_size': 16,
        'video_paths': short_video,
        'output_path': str(tmp_path / 'im_out'), 'tmp_path': str(tmp_path / 'tmp'),
    })
    from_import = create_extractor(args).extract(short_video)

    assert from_numpy['resnet'].shape == from_import['resnet'].shape
    for k in KEYS:
        np.testing.assert_allclose(np.asarray(from_numpy[k]),
                                   np.asarray(from_pickle[k]), atol=0,
                                   err_msg=f'numpy vs pickle: {k}')
        np.testing.assert_allclose(np.asarray(from_numpy[k]),
                                   np.asarray(from_import[k]), atol=1e-6,
                                   err_msg=f'cli vs import: {k}')


def test_cli_unknown_feature_type_lists_known(capsys):
    with pytest.raises(NotImplementedError, match='i3d'):
        cli.main(['feature_type=nonsense', 'video_paths=/dev/null'])


def test_file_list_run_and_resume(short_video, tmp_path, capsys):
    """file_with_video_paths drives multiple videos; a second run skips
    everything via the idempotent-output contract."""
    import shutil

    second = str(tmp_path / 'second_clip.mp4')
    shutil.copy(short_video, second)
    listfile = tmp_path / 'paths.txt'
    listfile.write_text(f'{short_video}\n{second}\n')

    argv = [
        'feature_type=resnet', 'model_name=resnet18', 'device=cpu',
        'batch_size=16', f'file_with_video_paths={listfile}',
        'on_extraction=save_numpy',
        f'output_path={tmp_path / "out"}', f'tmp_path={tmp_path / "tmp"}',
    ]
    assert cli.main(list(argv)) == 0
    out_dir = tmp_path / 'out' / 'resnet' / 'resnet18'
    assert len(list(out_dir.glob('*_resnet.npy'))) == 2

    capsys.readouterr()
    assert cli.main(list(argv)) == 0
    resumed = capsys.readouterr().out
    assert resumed.count('already exist') == 2


def test_video_shorter_than_stack_saves_empty(tmp_path, capsys):
    """A clip shorter than one stack yields (0, D) — saved with the empty-
    value warning, then skipped on resume (reference drops partial stacks)."""
    import cv2

    short5 = str(tmp_path / 'five_frames.mp4')
    w = cv2.VideoWriter(short5, cv2.VideoWriter_fourcc(*'mp4v'), 25, (64, 64))
    for i in range(5):
        w.write(np.full((64, 64, 3), i * 40, np.uint8))
    w.release()

    argv = [
        'feature_type=r21d', 'device=cpu', f'video_paths={short5}',
        'on_extraction=save_numpy',
        f'output_path={tmp_path / "out"}', f'tmp_path={tmp_path / "tmp"}',
    ]
    assert cli.main(list(argv)) == 0
    saved = np.load(
        tmp_path / 'out' / 'r21d' / 'r2plus1d_18_16_kinetics'
        / 'five_frames_r21d.npy')
    assert saved.shape == (0, 512)
    capsys.readouterr()
    assert cli.main(list(argv)) == 0   # resume loads the empty file cleanly
    assert 'already exist' in capsys.readouterr().out


def test_extraction_total_retimes_framewise(short_video, tmp_path):
    """extraction_total resamples the whole video to ~N frames. The pure
    index-resampling backend (no ffmpeg binary) is exact; an ffmpeg
    re-encode's fps filter may land a frame either side of N."""
    from video_features_tpu.io.video import which_ffmpeg

    args = load_config('resnet', overrides={
        'model_name': 'resnet18', 'device': 'cpu', 'batch_size': 16,
        'video_paths': short_video, 'extraction_total': 12,
        'output_path': str(tmp_path / 'out'), 'tmp_path': str(tmp_path / 'tmp'),
    })
    out = create_extractor(args).extract(short_video)
    n = out['resnet'].shape[0]
    assert out['resnet'].shape[1] == 512
    assert len(out['timestamps_ms']) == n
    if which_ffmpeg():
        assert 10 <= n <= 14
    else:
        assert n == 12
