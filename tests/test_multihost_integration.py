"""REAL 2-process `jax.distributed` integration for `multihost=true`.

tests/test_parallel.py covers the multihost wiring with a monkeypatched
`jax.distributed.initialize`; this test runs the actual runtime: two CPU
processes, process 0 hosting the coordinator service, each running the
REAL CLI (`python -m video_features_tpu ... multihost=true`) over the same
4-file worklist. The shared-nothing contract under test (reference
README.md:70-84 scale-out, made deterministic by parallel/worklist.py):
disjoint interleaved shards, every output file written, both processes
passing the final `sync_global_devices` barrier.
"""
import os
import socket
import subprocess
import sys
import wave
from pathlib import Path

import numpy as np
import pytest

pytestmark = pytest.mark.slow

REPO = Path(__file__).resolve().parents[1]


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(('127.0.0.1', 0))
        return s.getsockname()[1]


def _write_wav(path: Path, seconds: float, freq: float) -> None:
    sr = 16000
    t = np.arange(int(sr * seconds)) / sr
    pcm = (np.sin(2 * np.pi * freq * t) * 0.4 * 32767).astype('<i2')
    with wave.open(str(path), 'wb') as f:
        f.setnchannels(1)
        f.setsampwidth(2)
        f.setframerate(sr)
        f.writeframes(pcm.tobytes())


def test_two_process_multihost_cli(tmp_path):
    vids = []
    for i in range(4):
        p = tmp_path / f'clip_{i}.wav'
        _write_wav(p, 1.1, 220.0 * (i + 1))
        vids.append(str(p))
    worklist = tmp_path / 'paths.txt'
    worklist.write_text('\n'.join(vids) + '\n')

    port = _free_port()
    env = dict(os.environ, JAX_PLATFORMS='cpu')
    env.pop('VFT_ALLOW_RANDOM_WEIGHTS', None)  # exercise the config flag

    procs = []
    for rank in (0, 1):
        cmd = [sys.executable, '-m', 'video_features_tpu',
               'feature_type=vggish', 'device=cpu', 'multihost=true',
               f'coordinator_address=127.0.0.1:{port}',
               'num_processes=2', f'process_id={rank}',
               f'file_with_video_paths={worklist}',
               'allow_random_weights=true', 'batch_size=2',
               'on_extraction=save_numpy',
               f'output_path={tmp_path / "out"}',
               f'tmp_path={tmp_path / "tmp"}']
        procs.append(subprocess.Popen(
            cmd, env=env, cwd=str(REPO), text=True,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE))

    outs = []
    for rank, proc in enumerate(procs):
        stdout, stderr = proc.communicate(timeout=600)
        assert proc.returncode == 0, (
            f'rank {rank} failed:\n{stdout[-2000:]}\n{stderr[-2000:]}')
        outs.append(stdout)

    # disjoint interleaved coverage: rank 0 took videos 0,2; rank 1 took 1,3
    shards = []
    for stdout in outs:
        shards.append({v for v in vids if v in stdout})
    assert shards[0] == {vids[0], vids[2]}, shards
    assert shards[1] == {vids[1], vids[3]}, shards

    # every video's features landed on the shared filesystem
    from video_features_tpu.utils.output import make_path
    for v in vids:
        out_file = make_path(str(tmp_path / 'out' / 'vggish'), v, 'vggish',
                             '.npy')
        assert os.path.exists(out_file), out_file
        feats = np.load(out_file)
        assert feats.shape == (1, 128) and np.isfinite(feats).all()


def test_two_process_multihost_with_ingraph_dp(tmp_path):
    """The two distribution layers COMBINED, as a pod host would run them:
    2 real `jax.distributed` processes (worklist sharding, coordinator,
    barrier) × `data_parallel=true` (each process runs its shard's batches
    over a 4-virtual-device local mesh). Guards the seam the separate
    tests miss — device resolution under a multi-process runtime must stay
    LOCAL (jax.local_devices; the round-3 bug was `jax.devices()[0]` being
    pod-global), and the sharded step must produce single-device numerics.
    """
    vids = []
    for i in range(4):
        p = tmp_path / f'clip_{i}.wav'
        _write_wav(p, 4.2, 200.0 * (i + 1))   # 4 × 0.96 s vggish examples
        vids.append(str(p))
    worklist = tmp_path / 'paths.txt'
    worklist.write_text('\n'.join(vids) + '\n')

    port = _free_port()
    env = dict(os.environ, JAX_PLATFORMS='cpu',
               XLA_FLAGS='--xla_force_host_platform_device_count=4')

    procs = []
    for rank in (0, 1):
        cmd = [sys.executable, '-m', 'video_features_tpu',
               'feature_type=vggish', 'device=cpu', 'multihost=true',
               'data_parallel=true',
               f'coordinator_address=127.0.0.1:{port}',
               'num_processes=2', f'process_id={rank}',
               f'file_with_video_paths={worklist}',
               'allow_random_weights=true', 'batch_size=4',
               'on_extraction=save_numpy',
               f'output_path={tmp_path / "out"}',
               f'tmp_path={tmp_path / "tmp"}']
        procs.append(subprocess.Popen(
            cmd, env=env, cwd=str(REPO), text=True,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE))

    for rank, proc in enumerate(procs):
        stdout, stderr = proc.communicate(timeout=600)
        assert proc.returncode == 0, (
            f'rank {rank} failed:\n{stdout[-2000:]}\n{stderr[-2000:]}')

    # every output exists; numerics ≡ a plain single-process extraction
    from video_features_tpu.config import load_config
    from video_features_tpu.registry import create_extractor
    from video_features_tpu.utils.output import make_path
    args = load_config('vggish', overrides={
        'video_paths': vids[0], 'device': 'cpu',
        'allow_random_weights': True, 'batch_size': 4,
        'output_path': str(tmp_path / 'single'),
        'tmp_path': str(tmp_path / 'tmp_single'),
    })
    single = create_extractor(args).extract(vids[0])['vggish']
    for i, v in enumerate(vids):
        out_file = make_path(str(tmp_path / 'out' / 'vggish'), v, 'vggish',
                             '.npy')
        assert os.path.exists(out_file), out_file
        feats = np.load(out_file)
        assert feats.shape == (4, 128) and np.isfinite(feats).all()
        if i == 0:
            rel = (np.linalg.norm(feats - single)
                   / np.linalg.norm(single))
            # sharded conv scheduling reorders fp ops; ~2e-6 observed
            assert rel < 1e-5, f'multihost+DP vs single: rel L2 {rel}'
