"""BaseExtractor contract: resume, fault isolation, output actions, concat."""
import numpy as np
import pytest

from video_features_tpu.extract.base import BaseExtractor
from video_features_tpu.utils.output import load_numpy, load_pickle, make_path
from video_features_tpu.utils.slicing import form_slices, stack_indices


class StubExtractor(BaseExtractor):
    output_feat_keys = ['rgb', 'flow']

    def __init__(self, tmp_path, output_path, on_extraction='save_numpy',
                 concat_rgb_flow=True, fail=False):
        super().__init__('stub', on_extraction, str(tmp_path), str(output_path),
                         keep_tmp_files=False, device='cpu',
                         concat_rgb_flow=concat_rgb_flow)
        self.fail = fail
        self.calls = 0

    def extract(self, video_path):
        self.calls += 1
        if self.fail:
            raise RuntimeError('decode exploded')
        return {'rgb': np.ones((3, 4), np.float32),
                'flow': np.full((3, 4), 2.0, np.float32)}


def test_concat_and_rgb_naming(tmp_path):
    out = tmp_path / 'out'
    ex = StubExtractor(tmp_path / 'tmp', out)
    ex._extract('/videos/clip01.mp4')
    # concat saved under the no-suffix 'rgb' name; no flow file
    arr = load_numpy(str(out / 'clip01.npy'))
    assert arr.shape == (3, 8)
    assert (arr[:, :4] == 1).all() and (arr[:, 4:] == 2).all()
    assert not (out / 'clip01_flow.npy').exists()


def test_no_concat_saves_both_keys(tmp_path):
    out = tmp_path / 'out'
    ex = StubExtractor(tmp_path / 'tmp', out, concat_rgb_flow=False)
    ex._extract('/videos/clip01.mp4')
    assert load_numpy(str(out / 'clip01.npy')).shape == (3, 4)  # 'rgb' no suffix
    assert load_numpy(str(out / 'clip01_flow.npy')).shape == (3, 4)


def test_skip_if_exists(tmp_path):
    out = tmp_path / 'out'
    ex = StubExtractor(tmp_path / 'tmp', out)
    ex._extract('/videos/clip01.mp4')
    ex._extract('/videos/clip01.mp4')
    assert ex.calls == 1  # second run resumed/skipped


def test_corrupted_output_triggers_reextraction(tmp_path):
    out = tmp_path / 'out'
    ex = StubExtractor(tmp_path / 'tmp', out)
    ex._extract('/videos/clip01.mp4')
    (out / 'clip01.npy').write_bytes(b'garbage')
    ex._extract('/videos/clip01.mp4')
    assert ex.calls == 2
    assert load_numpy(str(out / 'clip01.npy')).shape == (3, 8)


def test_error_isolation(tmp_path, capsys):
    """The failure report goes through the structured log channel →
    stderr (video path + traceback); stdout — the feature stream under
    on_extraction=print — stays untouched (obs/events)."""
    ex = StubExtractor(tmp_path / 'tmp', tmp_path / 'out', fail=True)
    ex._extract('/videos/bad.mp4')  # must not raise
    captured = capsys.readouterr()
    assert 'An error occurred' not in captured.out
    assert 'bad.mp4' in captured.err
    assert 'decode exploded' in captured.err      # full traceback


def test_keyboard_interrupt_propagates(tmp_path):
    class KBStub(StubExtractor):
        def extract(self, video_path):
            raise KeyboardInterrupt

    ex = KBStub(tmp_path / 'tmp', tmp_path / 'out')
    with pytest.raises(KeyboardInterrupt):
        ex._extract('/videos/clip01.mp4')


def test_save_pickle_roundtrip(tmp_path):
    out = tmp_path / 'out'
    ex = StubExtractor(tmp_path / 'tmp', out, on_extraction='save_pickle')
    ex._extract('/videos/clip01.mp4')
    assert load_pickle(str(out / 'clip01.pkl')).shape == (3, 8)


def test_print_mode_never_skips(tmp_path, capsys):
    ex = StubExtractor(tmp_path / 'tmp', tmp_path / 'out', on_extraction='print')
    ex._extract('/videos/clip01.mp4')
    ex._extract('/videos/clip01.mp4')
    assert ex.calls == 2
    assert 'max:' in capsys.readouterr().out


def test_make_path_naming():
    assert make_path('/o', '/v/stem.mp4', 'rgb', '.npy') == '/o/stem.npy'
    assert make_path('/o', '/v/stem.mp4', 'fps', '.npy') == '/o/stem_fps.npy'


def test_form_slices():
    assert form_slices(100, 15, 15) == [(0, 15), (15, 30), (30, 45), (45, 60),
                                        (60, 75), (75, 90)]
    assert form_slices(10, 16, 16) == []  # shorter than one stack → dropped


def test_stack_indices_matches_form_slices():
    idx = stack_indices(100, 15, 15)
    assert idx.shape == (6, 15)
    assert idx[0, 0] == 0 and idx[-1, -1] == 89
    assert stack_indices(10, 16, 16).shape == (0, 16)
