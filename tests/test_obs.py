"""The flight recorder (obs/): span timeline, metrics registry,
Prometheus exposition, run manifest, structured error log — and the
contracts that pin their schemas.
"""
import json
import logging
import re
from pathlib import Path

import numpy as np
import pytest

from video_features_tpu.obs.metrics import (
    DEFAULT_BUCKETS, Histogram, MetricsRegistry,
)
from video_features_tpu.obs.spans import SpanRecorder
from video_features_tpu.utils.tracing import Tracer

from tools.make_sample_video import write_noise_clip as _write_clip  # noqa: E402
from tools.trace_view import validate_events  # noqa: E402


# -- span recorder -----------------------------------------------------------

def test_span_recorder_records_and_exports(tmp_path):
    rec = SpanRecorder(capacity=100)
    t0 = 1.0
    rec.span('decode', t0, t0 + 0.5, video='a.mp4')
    rec.instant('video_done', video='a.mp4', outcome='saved')
    events = rec.snapshot()
    spans = [e for e in events if e['ph'] == 'X']
    assert len(spans) == 1
    assert spans[0]['name'] == 'decode'
    assert spans[0]['args']['video'] == 'a.mp4'
    assert spans[0]['dur'] == pytest.approx(0.5e6)
    assert validate_events(events) == []

    out = tmp_path / 'trace.json'
    rec.export(str(out))
    doc = json.loads(out.read_text())
    assert isinstance(doc['traceEvents'], list)
    assert doc['otherData']['events_dropped'] == 0


def test_span_recorder_ring_buffer_drops_oldest():
    rec = SpanRecorder(capacity=4)
    for i in range(10):
        rec.span(f's{i}', float(i), float(i) + 0.1)
    assert rec.dropped == 6
    names = [e['name'] for e in rec.snapshot() if e['ph'] == 'X']
    assert names == ['s6', 's7', 's8', 's9']


def test_merge_traces_aligns_recorders_on_common_origin():
    """Recorders created at different times (serve workers built hours
    apart) share one CLOCK; the merged export must re-base everything to
    ONE origin so cross-worker ordering survives — each recorder's own
    snapshot re-bases to its own epoch."""
    from video_features_tpu.obs.spans import merge_traces
    a, b = SpanRecorder(capacity=8), SpanRecorder(capacity=8)
    a._t0, b._t0 = 100.0, 110.0            # b "built" 10s later
    a.span('a_span', 100.0, 100.5)
    b.span('b_span', 110.0, 110.5)
    # alone, each re-bases to its own epoch: both spans sit at ts=0
    assert [e['ts'] for e in a.snapshot() if e['ph'] == 'X'] == [0.0]
    assert [e['ts'] for e in b.snapshot() if e['ph'] == 'X'] == [0.0]
    merged = {e['name']: e for e in merge_traces([a, b])
              if e['ph'] == 'X'}
    assert merged['a_span']['ts'] == 0.0
    assert merged['b_span']['ts'] == pytest.approx(10e6)


def test_disabled_recorder_is_noop():
    rec = SpanRecorder(capacity=8, enabled=False)
    rec.span('x', 0.0, 1.0)
    rec.instant('y')
    assert [e for e in rec.snapshot() if e['ph'] != 'M'] == []


def test_tracer_feeds_recorder():
    """The stage table and the span timeline are two views over the SAME
    instrumentation sites: a tracer with a recorder attached both
    aggregates and appends span events, with attrs flowing through."""
    rec = SpanRecorder(capacity=100)
    t = Tracer(enabled=True, recorder=rec)
    with t.stage('model', video='v.mp4'):
        pass
    t.add('decode', 0.25, video='w.mp4')
    rep = t.report()
    assert rep['model']['count'] == 1 and rep['decode']['count'] == 1
    spans = {e['name']: e for e in rec.snapshot() if e['ph'] == 'X'}
    assert spans['model']['args']['video'] == 'v.mp4'
    assert spans['decode']['args']['video'] == 'w.mp4'
    assert spans['decode']['dur'] == pytest.approx(0.25e6, rel=1e-3)


def test_null_tracer_never_records():
    from video_features_tpu.utils.tracing import NULL_TRACER
    with NULL_TRACER.stage('x', video='v'):
        pass
    assert NULL_TRACER.report() == {}


# -- trace_view validation ---------------------------------------------------

def test_trace_view_rejects_violations(tmp_path):
    from tools.trace_view import main as trace_view_main
    bad = {'traceEvents': [
        {'name': 'a', 'ph': 'X', 'ts': 5.0, 'dur': 1.0, 'pid': 1, 'tid': 1},
        {'name': 'b', 'ph': 'X', 'ts': 2.0, 'dur': -1.0, 'pid': 1, 'tid': 1},
        {'name': 'c', 'ph': 'E', 'ts': 9.0, 'pid': 1, 'tid': 1},
        {'ph': 'X', 'ts': 1.0, 'pid': 1, 'tid': 1},
    ]}
    p = tmp_path / 'bad.json'
    p.write_text(json.dumps(bad))
    assert trace_view_main([str(p)]) == 1
    assert trace_view_main([str(tmp_path / 'missing.json')]) == 2


def test_trace_view_accepts_b_e_pairs(tmp_path):
    from tools.trace_view import main as trace_view_main
    good = {'traceEvents': [
        {'name': 'outer', 'ph': 'B', 'ts': 0.0, 'pid': 1, 'tid': 1},
        {'name': 'inner', 'ph': 'B', 'ts': 1.0, 'pid': 1, 'tid': 1},
        {'name': 'inner', 'ph': 'E', 'ts': 2.0, 'pid': 1, 'tid': 1},
        {'name': 'outer', 'ph': 'E', 'ts': 3.0, 'pid': 1, 'tid': 1},
    ]}
    p = tmp_path / 'good.json'
    p.write_text(json.dumps(good))
    assert trace_view_main([str(p), '--quiet']) == 0


# -- metrics registry + Prometheus exposition --------------------------------

_LABEL_VALUE = r'"(?:[^"\\]|\\.)*"'   # escaped \" \\ \n allowed inside
_SAMPLE_RE = re.compile(
    r'^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[a-zA-Z_][a-zA-Z0-9_]*=' + _LABEL_VALUE +
    r'(,[a-zA-Z_][a-zA-Z0-9_]*=' + _LABEL_VALUE + r')*\})? '
    r'(NaN|[+-]?Inf|[-+0-9.eE]+)$')


def assert_valid_prometheus(text: str) -> None:
    """Line-grammar check for the text exposition format 0.0.4."""
    assert text.endswith('\n')
    seen_type = {}
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith('# HELP ') or line.startswith('# TYPE '):
            parts = line.split(' ', 3)
            assert len(parts) >= 4 or parts[1] == 'TYPE', line
            if parts[1] == 'TYPE':
                seen_type[parts[2]] = parts[3]
            continue
        assert _SAMPLE_RE.match(line), f'bad sample line: {line!r}'
    assert seen_type, 'no TYPE lines'


def test_registry_counter_gauge_histogram_render():
    reg = MetricsRegistry()
    reg.counter('vft_requests_total', 'requests',
                labels={'outcome': 'completed'}).inc(3)
    reg.counter('vft_requests_total',
                labels={'outcome': 'failed'}).inc()
    reg.gauge('vft_queue_depth', 'queued videos').set(7)
    h = reg.histogram('vft_latency_seconds', 'latency',
                      buckets=(0.1, 1.0, 10.0))
    for v in (0.05, 0.5, 5.0, 50.0):
        h.observe(v)
    text = reg.render()
    assert_valid_prometheus(text)
    assert 'vft_requests_total{outcome="completed"} 3' in text
    assert 'vft_queue_depth 7' in text
    # cumulative buckets: 0.1→1, 1.0→2, 10→3, +Inf→4
    assert 'vft_latency_seconds_bucket{le="0.1"} 1' in text
    assert 'vft_latency_seconds_bucket{le="1"} 2' in text
    assert 'vft_latency_seconds_bucket{le="10"} 3' in text
    assert 'vft_latency_seconds_bucket{le="+Inf"} 4' in text
    assert 'vft_latency_seconds_count 4' in text
    assert 'vft_latency_seconds_sum 55.55' in text
    # re-registration returns the same series
    assert reg.gauge('vft_queue_depth').value == 7


def test_registry_rejects_type_conflicts_and_negative_inc():
    reg = MetricsRegistry()
    reg.counter('x_total')
    with pytest.raises(ValueError):
        reg.gauge('x_total')
    with pytest.raises(ValueError):
        reg.counter('y_total').inc(-1)


def test_prometheus_escaping_label_values_and_help():
    """Exposition-format escaping: label values escape backslash,
    double-quote, and newline; HELP text escapes backslash and newline
    (but NOT quotes — the 0.0.4 rules differ). Host labels injected by
    the fleet aggregator carry arbitrary operator strings, so a hostile
    value must not tear the line grammar."""
    reg = MetricsRegistry()
    reg.gauge('vft_up', 'backend "up"\nby host (C:\\fleet)',
              labels={'host': 'bad"host\\with\nnewline'}).set(1)
    text = reg.render()
    assert ('vft_up{host="bad\\"host\\\\with\\nnewline"} 1'
            in text.splitlines())
    # HELP: backslash and newline escaped, the quote left alone
    assert ('# HELP vft_up backend "up"\\nby host (C:\\\\fleet)'
            in text.splitlines())
    # no raw newline survived into the body of any line
    for line in text.splitlines():
        assert '\n' not in line
    assert_valid_prometheus(text)


def test_histogram_default_buckets_cover_latency_range():
    h = Histogram()
    assert h.buckets == tuple(sorted(DEFAULT_BUCKETS))
    h.observe(0.0)
    assert h.snapshot()['buckets'][0][1] == 1


def test_prometheus_from_serve_doc():
    """The serve metrics document renders to valid Prometheus text with
    the queue depth, pool hit rate, cache hits, and latency histogram
    the acceptance criteria name."""
    from video_features_tpu.obs.metrics import MetricsRegistry
    from video_features_tpu.serve import metrics as metrics_mod

    reg = MetricsRegistry()
    stats = metrics_mod.RequestStats(registry=reg)
    stats.bump('submitted')
    stats.bump('completed')
    stats.observe_latency(0.2)
    doc = metrics_mod.build_metrics(
        started_at=0.0, queue_depth=3, queue_capacity=64, draining=False,
        pool_stats={'size': 1, 'capacity': 4, 'hits': 5, 'misses': 1,
                    'hit_rate': 5 / 6, 'evictions': 0,
                    'builds_compiled': 1, 'builds_loaded': 0},
        request_stats=stats,
        stage_reports={'i3d': {'model': {
            'count': 4, 'total_s': 2.0, 'mean_s': 0.5, 'max_s': 0.9,
            'first_s': 0.9, 'occupancy': 0.75, 'occ_valid': 12,
            'occ_capacity': 16}}},
        cache_stats={'caches': 1, 'entries': 2, 'bytes': 10, 'hits': 7,
                     'misses': 3, 'hit_rate': 0.7, 'puts': 2,
                     'evictions': 0, 'corrupt_evicted': 0,
                     'bytes_saved': 123})
    text = metrics_mod.prometheus_text(doc, reg)
    assert_valid_prometheus(text)
    for needle in ('vft_serve_queue_depth 3',
                   'vft_warm_pool_hit_rate',
                   'vft_cache_hits 7',
                   'vft_serve_request_latency_seconds_bucket',
                   'vft_serve_requests_total{outcome="completed"} 1',
                   'vft_stage_seconds{stage="model"} 2',
                   'vft_stage_occupancy{stage="model"} 0.75'):
        assert needle in text, f'{needle!r} missing from:\n{text}'


# -- SLO burn-rate evaluation (obs/slo.py) -----------------------------------

def test_slo_burn_rate_trips_on_latency_spike():
    """Satellite/acceptance pin: an injected latency spike drives the
    burn rate over the 14.4x threshold in BOTH windows, fires the
    alert (gauges + alerts_total + WARNING event), and a recovery
    phase resolves it WITHOUT another FIRING transition."""
    from video_features_tpu.obs.events import event_counts
    from video_features_tpu.obs.slo import SloEvaluator

    clock = {'t': 1000.0}
    reg = MetricsRegistry()
    slo = SloEvaluator(reg, latency_p99_s=1.0,
                       clock=lambda: clock['t'])
    h = reg.histogram('vft_serve_request_latency_seconds')
    warn0 = event_counts().get(('WARNING', 'slo'), 0)

    slo.tick()                               # baseline sample
    for _ in range(100):
        h.observe(0.01)                      # clean traffic
    clock['t'] += 30
    doc = slo.tick()
    assert doc['enabled'] is True
    assert doc['alerts'] == {'latency_p99': False}
    assert all(v == 0.0 for v in doc['burn_rates']['latency'].values())

    for _ in range(50):
        h.observe(5.0)                       # the spike: 50 over 1.0s
    clock['t'] += 30
    doc = slo.tick()
    # 50/150 over threshold → frac 1/3 → burn ~33x against the 1%
    # budget, in both windows (both baselines predate the spike)
    assert doc['alerts'] == {'latency_p99': True}
    assert doc['alerts_firing'] == 1
    assert doc['alerts_total'] == 1
    for burn in doc['burn_rates']['latency'].values():
        assert burn > 14.4
    assert event_counts().get(('WARNING', 'slo'), 0) == warn0 + 1
    text = reg.render()
    assert_valid_prometheus(text)
    assert 'vft_slo_latency_burn_rate{window="5m"}' in text
    assert 'vft_slo_alert{slo="latency_p99"} 1' in text
    assert 'vft_slo_latency_threshold_seconds 1' in text

    # recovery: enough clean traffic that the 5m window's baseline
    # moves past the spike → short-window burn drops → alert resolves
    for _ in range(2000):
        h.observe(0.01)
    clock['t'] += 400
    doc = slo.tick()
    assert doc['alerts'] == {'latency_p99': False}
    assert doc['alerts_firing'] == 0
    assert doc['alerts_total'] == 1          # FIRING transitions only
    assert 'vft_slo_alert{slo="latency_p99"} 0' in reg.render()


def test_slo_availability_burn_rate():
    """The availability objective burns on the failed-request fraction:
    10% failures against a 99.9% target is a 100x burn."""
    from video_features_tpu.obs.slo import SloEvaluator

    clock = {'t': 0.0}
    reg = MetricsRegistry()
    slo = SloEvaluator(reg, availability=0.999,
                       clock=lambda: clock['t'])
    slo.tick()
    reg.counter('vft_serve_requests_total',
                labels={'outcome': 'completed'}).inc(90)
    reg.counter('vft_serve_requests_total',
                labels={'outcome': 'failed'}).inc(10)
    clock['t'] += 60
    doc = slo.tick()
    for burn in doc['burn_rates']['availability'].values():
        assert burn == pytest.approx(100.0)
    assert doc['alerts'] == {'availability': True}
    assert 'vft_slo_availability_burn_rate{window="1h"}' in reg.render()


def test_slo_evaluator_rejects_bad_objectives():
    from video_features_tpu.obs.slo import SloEvaluator, disabled_stats
    reg = MetricsRegistry()
    with pytest.raises(ValueError):
        SloEvaluator(reg)                    # no objective at all
    with pytest.raises(ValueError):
        SloEvaluator(reg, latency_p99_s=0.0)
    with pytest.raises(ValueError):
        SloEvaluator(reg, availability=1.5)
    # the disabled shape carries the same keys as a live evaluation
    live = SloEvaluator(reg, latency_p99_s=1.0, clock=lambda: 0.0).tick()
    assert set(disabled_stats()) <= set(live)


# -- structured event log ----------------------------------------------------

def _make_stub(tmp_path, on_extraction, fail=True):
    from video_features_tpu.extract.base import BaseExtractor

    class Stub(BaseExtractor):
        output_feat_keys = ['rgb']

        def extract(self, video_path):
            if fail:
                raise RuntimeError('decode exploded')
            return {'rgb': np.ones((2, 3), np.float32)}

    return Stub('stub', on_extraction, str(tmp_path / 'tmp'),
                str(tmp_path / 'out'), keep_tmp_files=False, device='cpu')


def test_error_log_keeps_print_mode_stdout_clean(tmp_path, capsys, caplog):
    """The fault-isolation error report must never interleave with the
    feature stream: stdout stays byte-clean, the structured record (video
    path + traceback) lands on the logging channel → stderr."""
    ex = _make_stub(tmp_path, 'print')
    with caplog.at_level(logging.WARNING, logger='video_features_tpu'):
        ex._extract('/videos/bad.mp4')          # must not raise
    captured = capsys.readouterr()
    assert captured.out == ''                   # byte-clean feature stream
    assert 'bad.mp4' in captured.err
    assert 'RuntimeError' in captured.err       # full traceback, stderr
    rec = next(r for r in caplog.records if getattr(r, 'video', None))
    assert rec.levelno == logging.WARNING
    assert rec.video == '/videos/bad.mp4'
    assert rec.exc_info is not None


def test_packed_device_step_error_goes_to_logger(tmp_path, capsys, caplog):
    """parallel/packing.py's device-step fault isolation reports through
    the same structured channel — batch videos named, stdout untouched."""
    from video_features_tpu.obs.events import log_batch_error
    with caplog.at_level(logging.WARNING, logger='video_features_tpu'):
        try:
            raise RuntimeError('geometry will not compile')
        except RuntimeError:
            log_batch_error(['a.mp4', 'b.mp4'], valid=3, batch=4)
    captured = capsys.readouterr()
    assert captured.out == ''
    assert 'a.mp4' in captured.err and 'geometry will not compile' in captured.err
    rec = next(r for r in caplog.records if getattr(r, 'videos', None))
    assert rec.valid == 3 and rec.batch == 4


# -- the packed CLI run: trace + manifest end to end -------------------------

@pytest.fixture(scope='module')
def obs_worklist(tmp_path_factory):
    d = tmp_path_factory.mktemp('obsvids')
    return [str(_write_clip(d / f'v{i}.mp4', n, seed=10 + i))
            for i, n in enumerate((6, 9))]


def test_packed_cli_trace_out_covers_every_video(obs_worklist, tmp_path,
                                                 capsys):
    """Acceptance: one packed CLI run with trace_out yields a Chrome
    trace whose spans cover decode/pack/device-step/save for EVERY video
    in the worklist, and tools/trace_view.py validates it."""
    from tools.trace_view import main as trace_view_main
    from video_features_tpu.cli import main

    trace = tmp_path / 'trace.json'
    manifest = tmp_path / 'manifest.json'
    rc = main([
        'feature_type=resnet', 'model_name=resnet18', 'device=cpu',
        f'video_paths=[{",".join(obs_worklist)}]',
        'pack_across_videos=true', 'batch_size=4',
        'allow_random_weights=true', 'on_extraction=save_numpy',
        f'output_path={tmp_path / "out"}', f'tmp_path={tmp_path / "tmp"}',
        f'trace_out={trace}', f'manifest_out={manifest}'])
    assert rc == 0
    capsys.readouterr()

    doc = json.loads(trace.read_text())
    events = doc['traceEvents']
    assert validate_events(events) == []
    spans = [e for e in events if e['ph'] == 'X']
    by_name = {}
    for e in spans:
        by_name.setdefault(e['name'], []).append(e)
    for path in obs_worklist:
        assert any(e['args'].get('video') == path
                   for e in by_name.get('decode+preprocess', [])
                   if 'args' in e), f'no decode span for {path}'
        assert any(path in e['args'].get('videos', [])
                   for e in by_name.get('pack', []) if 'args' in e), \
            f'no pack span for {path}'
        assert any(path in e['args'].get('videos', [])
                   for e in by_name.get('model', []) if 'args' in e), \
            f'no device-step span for {path}'
        # the deferred readback is its own stage with the same
        # provenance/occupancy attrs — the timeline must show model
        # (dispatch+compute) and d2h (readback) as DISTINCT spans
        assert any(path in e['args'].get('videos', [])
                   and e['args'].get('capacity')
                   for e in by_name.get('d2h', []) if 'args' in e), \
            f'no d2h span for {path}'
        assert any(e['args'].get('video') == path
                   for e in by_name.get('save', []) if 'args' in e), \
            f'no save span for {path}'
    # no time lost or double-counted: every dispatched batch has exactly
    # one model span and one d2h span
    assert len(by_name.get('d2h', [])) == len(by_name.get('model', []))
    # every model/d2h span names the precision lane that computed it
    # (compute_dtype — the bf16 fast lane's post-hoc attribution hook);
    # this run is the default lane, so every span says float32
    for name in ('model', 'd2h'):
        assert all(e['args'].get('compute_dtype') == 'float32'
                   for e in by_name.get(name, []) if 'args' in e), name
    # vft-flight: a packed CLI run is ONE request — every trace-tagged
    # span shares the run's single trace_id (per-video child span_ids
    # under it), so --trace-id filtering works on CLI traces too
    run_tids = {e['args']['trace_id'] for e in spans
                if 'args' in e and 'trace_id' in e['args']}
    assert len(run_tids) == 1, run_tids
    assert all('span_id' in e['args'] for e in spans
               if 'args' in e and 'trace_id' in e['args'])
    # the validator tool accepts the real artifact (tier-1 exercise)
    assert trace_view_main([str(trace), '--quiet']) == 0
    capsys.readouterr()

    # -- run manifest: fingerprints + outcomes + stages ----------------------
    man = json.loads(manifest.read_text())
    assert man['schema'] == 'video_features_tpu.run_manifest/1'
    assert man['fingerprints']['run']
    assert man['fingerprints']['config']
    assert set(man['videos']) == set(obs_worklist)
    assert all(v['outcome'] == 'saved' for v in man['videos'].values())
    assert man['outcomes'] == {'saved': len(obs_worklist)}
    assert 'model' in man['stages'] and man['stages']['model']['count'] > 0
    assert man['config']['feature_type'] == 'resnet'
    # outputs written normally alongside the telemetry
    from video_features_tpu.utils.output import make_path
    for p in obs_worklist:
        arr = np.load(make_path(str(tmp_path / 'out' / 'resnet' /
                                    'resnet18'), p, 'resnet', '.npy'))
        assert arr.shape[1] == 512


def test_one_shot_cli_trace_and_manifest(obs_worklist, tmp_path, capsys):
    """The per-video loop records the same telemetry: a video span per
    clip plus the stage spans, and a manifest with per-video outcomes."""
    from video_features_tpu.cli import main

    trace = tmp_path / 'trace.json'
    manifest = tmp_path / 'manifest.json'
    rc = main([
        'feature_type=resnet', 'model_name=resnet18', 'device=cpu',
        f'video_paths=[{",".join(obs_worklist)}]', 'batch_size=4',
        'allow_random_weights=true', 'on_extraction=save_numpy',
        f'output_path={tmp_path / "out"}', f'tmp_path={tmp_path / "tmp"}',
        f'trace_out={trace}', f'manifest_out={manifest}'])
    assert rc == 0
    capsys.readouterr()
    events = json.loads(trace.read_text())['traceEvents']
    assert validate_events(events) == []
    vids = [e for e in events if e['ph'] == 'X' and e['name'] == 'video']
    assert {e['args']['video'] for e in vids} == set(obs_worklist)
    assert all(e['args']['outcome'] == 'saved' for e in vids)
    man = json.loads(manifest.read_text())
    assert man['outcomes'] == {'saved': len(obs_worklist)}
    assert man['stages']                       # folded across the reset


# -- serve: Prometheus endpoint + file mirror --------------------------------

def test_serve_prometheus_endpoint_and_mirror(tmp_path):
    from video_features_tpu.serve.client import ServeClient
    from video_features_tpu.serve.server import ExtractionServer

    metrics_path = str(tmp_path / 'metrics.json')
    server = ExtractionServer(metrics_path=metrics_path).start()
    try:
        client = ServeClient(port=server.port)
        text = client.metrics_prom()
        assert_valid_prometheus(text)
        for needle in ('vft_serve_queue_depth 0',
                       'vft_serve_queue_capacity 64',
                       'vft_warm_pool_hit_rate',
                       'vft_cache_hits',
                       'vft_inflight_batches 0',
                       'vft_serve_request_latency_seconds_count',
                       'vft_serve_uptime_seconds'):
            assert needle in text, f'{needle!r} missing from:\n{text}'
    finally:
        server.drain(wait=True, grace_s=30)
    # the atomic mirror wrote BOTH formats on drain
    doc = json.loads(Path(metrics_path).read_text())
    assert 'queue' in doc
    prom = Path(metrics_path + '.prom').read_text()
    assert_valid_prometheus(prom)
    assert 'vft_serve_draining 1' in prom


def test_serve_drain_exports_merged_trace(obs_worklist, tmp_path):
    """A server-wide trace_out base override stitches EVERY worker's
    recorder into one Chrome trace at drain — spans from a real request
    (decode/pack/model/save, request ids) survive the merge and the
    export validates. vft-flight acceptance rides the same request: the
    caller's traceparent is adopted, the live ``trace`` command
    assembles admission/pack/model/d2h/save spans sharing that one
    trace_id (farm decode spans are exercised in tests/test_farm.py),
    and the ids survive into the merged export."""
    from video_features_tpu.serve.client import ServeClient
    from video_features_tpu.serve.server import ExtractionServer

    trace = tmp_path / 'serve_trace.json'
    server = ExtractionServer(base_overrides={
        'device': 'cpu', 'model_name': 'resnet18', 'batch_size': 4,
        'allow_random_weights': True, 'on_extraction': 'save_numpy',
        'tmp_path': str(tmp_path / 'serve_tmp'),
        'output_path': str(tmp_path / 'serve_out'),
        'trace_out': str(trace),
    }, queue_depth=8, pool_size=2).start()
    caller_trace = 'c0ffee5e1f00d5c0ffee5e1f00d5c0ff'
    try:
        client = ServeClient(port=server.port)
        rid = client.submit(
            'resnet', [obs_worklist[0]],
            traceparent=f'00-{caller_trace}-00f067aa0ba902b7-01')
        st = client.wait(rid, timeout_s=300)
        assert st['state'] == 'done', st
        # the caller's trace id was ADOPTED, not re-minted
        assert st['trace_id'] == caller_trace, st
        # the live /trace assembly: one request's spans, one trace_id,
        # covering admission + pack + model + d2h + save
        tr = client.trace(rid)
        assert tr['trace_id'] == caller_trace
        names = {e['name'] for e in tr['events']}
        for stage in ('admission', 'pack', 'model', 'd2h', 'save'):
            assert stage in names, (stage, sorted(names))
        for e in tr['events']:
            args = e.get('args') or {}
            assert (args.get('trace_id') == caller_trace
                    or caller_trace in (args.get('trace_ids') or ())
                    or args.get('request_id') == rid), e
        # ts-sorted (the route contract)
        ts = [e['ts'] for e in tr['events']]
        assert ts == sorted(ts)
        # ANOTHER request must not leak into this one's trace
        rid2 = client.submit('resnet', [obs_worklist[1]])
        client.wait(rid2, timeout_s=300)
        tr2 = client.trace(rid2)
        assert tr2['trace_id'] != caller_trace
        assert all((e.get('args') or {}).get('video') != obs_worklist[0]
                   for e in tr2['events'])
    finally:
        server.drain(wait=True, grace_s=120)

    doc = json.loads(trace.read_text())
    events = doc['traceEvents']
    assert validate_events(events) == []
    assert doc['otherData']['recorders_merged'] >= 1
    spans = [e for e in events if e['ph'] == 'X' and 'args' in e]
    assert any(e['name'] == 'model' for e in spans)
    assert any(e['name'] == 'save'
               and e['args'].get('video') == obs_worklist[0]
               and e['args'].get('request_id') == rid for e in spans)
    # the trace ids survive the merged export too
    assert any(e['args'].get('trace_id') == caller_trace for e in spans)


# -- bench_diff --------------------------------------------------------------

def _bench_rec(**rungs):
    return {'metric': 'm', 'value': rungs.get('value', 1.0), 'unit': 'u',
            'vs_baseline': 1.0, 'rungs': rungs}


def test_bench_diff_detects_direction_aware_regressions(tmp_path, capsys):
    from tools.bench_diff import main as bench_diff_main
    old = tmp_path / 'old.json'
    new = tmp_path / 'new.json'
    old.write_text(json.dumps(_bench_rec(
        e2e_mixed=10.0, serve_p99_latency_s=1.0, only_old=5.0)))
    # throughput dropped 50% AND latency doubled: both are regressions
    new.write_text(json.dumps(_bench_rec(
        e2e_mixed=5.0, serve_p99_latency_s=2.0, only_new='err')))
    assert bench_diff_main([str(old), str(new)]) == 0   # report-only mode
    capsys.readouterr()
    assert bench_diff_main([str(old), str(new),
                            '--fail-on-regression', '10']) == 1
    err = capsys.readouterr().err
    assert 'e2e_mixed' in err and 'serve_p99_latency_s' in err

    # within threshold → pass
    new.write_text(json.dumps(_bench_rec(
        e2e_mixed=9.8, serve_p99_latency_s=1.02)))
    assert bench_diff_main([str(old), str(new),
                            '--fail-on-regression', '10']) == 0
    assert bench_diff_main([str(tmp_path / 'nope.json'), str(new)]) == 2


def test_bench_diff_latency_improvement_is_not_regression(tmp_path):
    from tools.bench_diff import main as bench_diff_main
    old = tmp_path / 'o.json'
    new = tmp_path / 'n.json'
    old.write_text(json.dumps(_bench_rec(serve_p50_latency_s=2.0)))
    new.write_text(json.dumps(_bench_rec(serve_p50_latency_s=0.5)))
    assert bench_diff_main([str(old), str(new),
                            '--fail-on-regression', '1']) == 0


# -- schema contracts --------------------------------------------------------

TRACER_RECORD_KEYS = {'count', 'total_s', 'mean_s', 'max_s', 'first_s',
                      'ramp', 'occupancy', 'occ_valid', 'occ_capacity',
                      # mesh-sharded batches: per-device slot ledger
                      'occ_device'}
METRICS_DOC_KEYS = {'uptime_s', 'queue', 'warm_pool', 'cache', 'farm',
                    'requests', 'latency', 'stages', 'stages_merged',
                    'inflight_batches',
                    # persistent executable store (aot/): merged store
                    # counters + programs_loaded/programs_compiled —
                    # the zero-cold-start audit pair (all-zero without
                    # aot_enabled)
                    'aot',
                    # sharded feature index (index/): rows/shards/
                    # ingest-lag + query counters, {'enabled': False}
                    # without index_enabled
                    'index',
                    # network front door (ingress/): per-tenant view,
                    # {'enabled': False, ...} on loopback-only servers
                    'ingress',
                    # vft-flight: structured-event counts (the
                    # vft_events_total mirror's source), span-ring view
                    # (recorders + events_dropped), and the stall
                    # watchdog's progress ledger ({'enabled': False}
                    # without watchdog_stall_s)
                    'events', 'trace', 'watchdog',
                    # vft-scope: SLO burn-rate evaluation (obs/slo.py),
                    # {'enabled': False, ...} without slo_* knobs
                    'slo'}
TRACE_EVENT_KEYS = {'name', 'ph', 'ts', 'dur', 'pid', 'tid', 'args', 's'}
MANIFEST_KEYS = {'schema', 'version', 'started_at_unix_s', 'wall_s',
                 'config', 'fingerprints', 'videos', 'outcomes', 'stages',
                 'compile', 'executables', 'farm', 'mesh', 'ingress',
                 'programs_lock', 'aot', 'index', 'slo'}


CANONICAL_STAGES = {'decode', 'decode+preprocess', 'audio_dsp',
                    'queue_idle', 'pack', 'h2d', 'model', 'd2h', 'save',
                    'cache_lookup', 'cache_publish'}


def test_stage_vocabulary_contract():
    """Pin the canonical stage names (utils.tracing.STAGES): dashboards
    key vft_stage_* families and bench stage_reports on them — renaming
    or dropping one (e.g. folding d2h back into model) must be an
    intentional, test-visible event."""
    from video_features_tpu.utils.tracing import STAGES
    assert set(STAGES) == CANONICAL_STAGES
    assert 'model' in STAGES and 'd2h' in STAGES    # split, not aliased


def test_merge_reports_keeps_model_and_d2h_distinct():
    """Fleet-wide merges (serve metrics, retired-worker history) must
    keep the dispatch and readback stages separate — their shares sum to
    the old all-in 'model' share, so folding them would re-launder
    readback into compute."""
    from video_features_tpu.utils.tracing import merge_reports
    a = {'model': {'count': 2, 'total_s': 1.0, 'max_s': 0.6,
                   'first_s': 0.6},
         'd2h': {'count': 2, 'total_s': 0.5, 'max_s': 0.3, 'first_s': 0.3,
                 'occ_valid': 6, 'occ_capacity': 8}}
    b = {'model': {'count': 1, 'total_s': 0.4, 'max_s': 0.4,
                   'first_s': 0.4},
         'd2h': {'count': 1, 'total_s': 0.1, 'max_s': 0.1, 'first_s': 0.1,
                 'occ_valid': 4, 'occ_capacity': 4}}
    merged = merge_reports([a, b])
    assert merged['model']['total_s'] == pytest.approx(1.4)
    assert merged['d2h']['total_s'] == pytest.approx(0.6)
    assert merged['d2h']['occupancy'] == pytest.approx(10 / 12)


def test_schema_contract_key_sets(tmp_path):
    """Pin the three export schemas: a key rename is an intentional,
    test-visible event — scrapers and dashboards depend on these."""
    # tracer report records
    t = Tracer()
    with t.stage('a'):
        pass
    with t.stage('a'):
        pass
    t.add_occupancy('a', 3, 4)
    rec = t.report()['a']
    assert set(rec) <= TRACER_RECORD_KEYS
    assert {'count', 'total_s', 'mean_s', 'max_s', 'first_s'} <= set(rec)

    # serve metrics document
    from video_features_tpu.serve import metrics as metrics_mod
    doc = metrics_mod.build_metrics(
        started_at=0.0, queue_depth=0, queue_capacity=1, draining=False,
        pool_stats={}, request_stats=metrics_mod.RequestStats(),
        stage_reports={})
    assert set(doc) == METRICS_DOC_KEYS
    assert set(doc['requests']) == {'submitted', 'completed', 'failed',
                                    'rejected', 'expired_videos',
                                    'cached_videos'}

    # trace events
    sr = SpanRecorder(capacity=8)
    sr.span('s', 0.0, 1.0, video='v')
    sr.instant('i')
    for ev in sr.snapshot():
        assert set(ev) <= TRACE_EVENT_KEYS, ev

    # run manifest
    from video_features_tpu.obs.manifest import RunManifest
    man = RunManifest({'feature_type': 'resnet'}).document()
    assert set(man) == MANIFEST_KEYS


# -- vft-flight: trace context ------------------------------------------------


def test_trace_context_mint_parse_roundtrip():
    from video_features_tpu.obs.context import (
        TraceContext, accept_traceparent, mint, parse_traceparent,
    )
    ctx = mint()
    assert len(ctx.trace_id) == 32 and len(ctx.span_id) == 16
    # wire form round-trips: same trace, NEW span per hop
    hop = parse_traceparent(ctx.traceparent())
    assert hop.trace_id == ctx.trace_id
    assert hop.span_id != ctx.span_id
    # children stay under the parent's trace
    child = ctx.child()
    assert child.trace_id == ctx.trace_id
    assert child.span_id != ctx.span_id
    assert set(ctx.attrs()) == {'trace_id', 'span_id'}
    # malformed / absent / all-zero headers degrade to None (and
    # accept_traceparent to a fresh mint), never to garbage ids
    for bad in (None, '', 'not-a-traceparent',
                '00-' + '0' * 32 + '-00f067aa0ba902b7-01',
                '00-' + 'a' * 32 + '-' + '0' * 16 + '-01',
                'ff-' + 'a' * 32 + '-00f067aa0ba902b7-01',
                '00-a' * 20):
        assert parse_traceparent(bad) is None, bad
        assert isinstance(accept_traceparent(bad), TraceContext)
    # uppercase hex normalizes (the W3C header is case-insensitive)
    up = parse_traceparent('00-' + 'A' * 32 + '-00F067AA0BA902B7-01')
    assert up is not None and up.trace_id == 'a' * 32


def test_trace_attrs_helper_tolerates_legacy_tasks():
    from video_features_tpu.obs.context import mint, trace_attrs
    from video_features_tpu.parallel.packing import VideoTask
    assert trace_attrs(VideoTask('a.mp4')) == {}
    assert trace_attrs(object()) == {}
    ctx = mint()
    t = VideoTask('a.mp4', trace=ctx)
    assert trace_attrs(t) == ctx.attrs()


# -- vft-flight: spans bugfixes (bytes rendering, bounded snapshot) ----------


def test_jsonable_renders_bytes_ascii_safely_with_cap():
    from video_features_tpu.obs.spans import _jsonable
    assert _jsonable(b'hello') == 'hello'
    assert "b'" not in _jsonable(b'hello')        # the old str() bug
    # non-ASCII bytes escape instead of raising (ASCII-safe contract)
    out = _jsonable(b'\xff\x00ok')
    assert isinstance(out, str) and 'ok' in out
    out.encode('ascii')                            # must be pure ASCII
    # length cap: a stray frame buffer must not balloon the export
    big = _jsonable(b'x' * 10_000)
    assert len(big) < 1_000 and '(+' in big
    json.dumps({'v': _jsonable(b'\xff' * 300)})    # always JSON-safe


def test_snapshot_limit_bounds_events():
    rec = SpanRecorder(capacity=1000)
    for i in range(100):
        rec.span(f's{i}', float(i), float(i) + 0.5)
    full = [e for e in rec.snapshot() if e['ph'] == 'X']
    assert len(full) == 100
    tail = [e for e in rec.snapshot(limit=10) if e['ph'] == 'X']
    assert len(tail) == 10
    # MOST RECENT events, still ts-sorted, same origin semantics
    assert [e['name'] for e in tail] == [f's{i}' for i in range(90, 100)]
    assert validate_events(rec.snapshot(limit=10)) == []
    # limit >= len is the full snapshot
    assert len([e for e in rec.snapshot(limit=500)
                if e['ph'] == 'X']) == 100


def test_span_pid_tid_override_for_cross_process_spans():
    """Farm decode spans are recorded by the parent but MEASURED in the
    worker: pid/tid overrides put them in the worker's own lane."""
    rec = SpanRecorder(capacity=16)
    rec.span('decode', 1.0, 1.5, pid=4242, tid=7, video='v.mp4')
    rec.span('local', 2.0, 2.5)
    import os as _os
    by_name = {e['name']: e for e in rec.snapshot() if e['ph'] == 'X'}
    assert by_name['decode']['pid'] == 4242
    assert by_name['decode']['tid'] == 7
    assert by_name['local']['pid'] == _os.getpid()
    assert validate_events(rec.snapshot()) == []


# -- vft-flight: event counters + tail ---------------------------------------


def test_event_counts_and_tail_feed_metrics_and_blackbox(caplog):
    from video_features_tpu.obs.events import (
        event, event_counts, events_tail,
    )
    before = event_counts().get(('WARNING', 'testsub'), 0)
    with caplog.at_level(logging.WARNING, logger='video_features_tpu'):
        event(logging.WARNING, 'something odd', subsystem='testsub',
              video='v.mp4', request_id='r1')
    counts = event_counts()
    assert counts[('WARNING', 'testsub')] == before + 1
    tail = events_tail()
    rec = next(r for r in reversed(tail)
               if r.get('subsystem') == 'testsub')
    assert rec['level'] == 'WARNING' and rec['msg'] == 'something odd'
    assert rec['fields'] == {'video': 'v.mp4', 'request_id': 'r1'}
    # exc_info captures the traceback text for the black box
    with caplog.at_level(logging.WARNING, logger='video_features_tpu'):
        try:
            raise RuntimeError('boom for tail')
        except RuntimeError:
            event(logging.ERROR, 'it died', subsystem='testsub',
                  exc_info=True)
    rec = events_tail()[-1]
    assert 'boom for tail' in rec.get('exc', '')


def test_prometheus_mirrors_events_and_trace_dropped():
    """vft_events_total{level,subsystem} and
    vft_trace_events_dropped_total are COUNTERS mirrored by delta —
    repeated renders never double-count, and a recorder aging out of
    the bounded deque (sum dips) never decrements."""
    import logging as _logging

    from video_features_tpu.obs.events import event
    from video_features_tpu.obs.metrics import MetricsRegistry
    from video_features_tpu.serve import metrics as metrics_mod
    event(_logging.WARNING, 'mirror me', subsystem='mirrorsub')
    reg = MetricsRegistry()
    stats = metrics_mod.RequestStats(registry=reg)

    def render(dropped):
        doc = metrics_mod.build_metrics(
            started_at=0.0, queue_depth=0, queue_capacity=1,
            draining=False, pool_stats={}, request_stats=stats,
            stage_reports={},
            trace_stats={'recorders': 2, 'events_dropped': dropped})
        assert set(doc['trace']) == {'recorders', 'events_dropped'}
        assert doc['events']['total'] >= 1
        return metrics_mod.prometheus_text(doc, reg)

    text = render(7)
    assert_valid_prometheus(text)
    assert ('vft_events_total{level="WARNING",subsystem="mirrorsub"}'
            in text)
    assert 'vft_trace_events_dropped_total 7' in text
    # stable under re-render; a DIP (recorder eviction) never decrements
    assert 'vft_trace_events_dropped_total 7' in render(7)
    assert 'vft_trace_events_dropped_total 7' in render(3)
    assert 'vft_trace_events_dropped_total 9' in render(9)
    assert 'vft_watchdog_enabled 0' in text


# -- vft-flight: stall watchdog ----------------------------------------------


def _fake_clock(start=1000.0):
    state = {'t': start}

    def clock():
        return state['t']

    return clock, state


def test_watchdog_fires_on_stall_quiet_on_empty_queue():
    from video_features_tpu.obs.metrics import MetricsRegistry
    from video_features_tpu.obs.watchdog import StallWatchdog
    clock, state = _fake_clock()
    stalls = []
    reg = MetricsRegistry()
    wd = StallWatchdog(5.0, on_stall=stalls.append, registry=reg,
                       clock=clock)
    # idle-but-EMPTY: no pending work → silence forever
    wd.advance('w0', 'model')
    state['t'] += 1000
    assert wd.check() == []
    # pending work + advances → quiet
    wd.set_pending('w0', 3)
    wd.advance('w0', 'decode')
    state['t'] += 4.0
    wd.advance('w0', 'model')
    state['t'] += 4.0
    assert wd.check() == []
    # pending work + NO advance past the deadline → one trip, attributed
    # to the last stage that advanced
    state['t'] += 6.0
    fired = wd.check()
    assert len(fired) == 1 and fired[0]['worker'] == 'w0'
    assert fired[0]['stage'] == 'model' and fired[0]['pending'] == 3
    assert stalls == fired
    # a tripped worker does NOT re-trip until it advances again
    state['t'] += 100.0
    assert wd.check() == []
    wd.advance('w0', 'd2h')
    state['t'] += 6.0
    assert len(wd.check()) == 1
    assert wd.stalls_total == 2
    # the counter family carries the stage label
    text = reg.render()
    assert 'vft_watchdog_stalls_total{stage="model"} 1' in text
    assert 'vft_watchdog_stalls_total{stage="d2h"} 1' in text
    snap = wd.snapshot()
    assert snap['enabled'] and snap['stalls_total'] == 2
    assert snap['workers']['w0']['pending'] == 3


def test_watchdog_new_work_resets_clock_and_never_started_stage():
    from video_features_tpu.obs.watchdog import (
        STAGE_NOT_STARTED, StallWatchdog,
    )
    clock, state = _fake_clock()
    wd = StallWatchdog(5.0, clock=clock)
    wd.set_pending('w1', 1)
    state['t'] += 3.0
    wd.set_pending('w1', 0)          # drained before the deadline
    state['t'] += 100.0
    assert wd.check() == []          # long-idle, empty: quiet
    wd.set_pending('w1', 2)          # NEW work: full stall_s restarts
    state['t'] += 4.0
    assert wd.check() == []
    state['t'] += 2.0
    fired = wd.check()
    # queued work that never started attributes to 'admission'
    assert len(fired) == 1 and fired[0]['stage'] == STAGE_NOT_STARTED
    wd.forget('w1')
    assert wd.snapshot()['workers'] == {}


def test_watchdog_rides_tracer_progress_hook():
    """The ledger feeds off the SAME instrumentation sites as the stage
    table: a Tracer with a progress hook advances the ledger on every
    add/stage, with farm-worker attribution via the worker attr."""
    from video_features_tpu.obs.watchdog import StallWatchdog
    clock, state = _fake_clock()
    wd = StallWatchdog(5.0, clock=clock)
    t = Tracer(enabled=True)
    t.progress = lambda stage, worker=None: (
        wd.advance('lbl', stage),
        wd.advance(f'lbl/farm-w{worker}', stage)
        if worker is not None else None)
    with t.stage('model'):
        pass
    t.add('decode', 0.1, worker=3)
    snap = wd.snapshot()['workers']
    assert snap['lbl']['stage'] == 'decode'
    assert snap['lbl/farm-w3']['stage'] == 'decode'


# -- vft-flight: black box ---------------------------------------------------


def _make_blackbox(tmp_path, **kw):
    from video_features_tpu.obs.blackbox import BlackBox
    rec = SpanRecorder(capacity=64)
    rec.span('model', 1.0, 2.0, video='v.mp4')
    kw.setdefault('recorders', lambda: [rec])
    kw.setdefault('min_interval_s', 0.0)
    return BlackBox(str(tmp_path / 'postmortem'), **kw), rec


def test_blackbox_bundle_layout_and_validation(tmp_path):
    from video_features_tpu.obs.blackbox import validate_bundle
    from video_features_tpu.obs.events import event
    event(logging.WARNING, 'pre-crash breadcrumb', subsystem='obs')
    bb, _ = _make_blackbox(
        tmp_path,
        metrics_fn=lambda: {'queue': {'depth': 1}},
        prom_fn=lambda: 'vft_x 1\n',
        manifest_fn=lambda: {'schema': 'frag', 'videos': {}})
    bundle = bb.dump('worker_crash', label='resnet/resnet18')
    assert bundle is not None
    assert validate_bundle(bundle) == []
    meta = json.loads((Path(bundle) / 'meta.json').read_text())
    assert meta['reason'] == 'worker_crash'
    assert meta['extra']['label'] == 'resnet/resnet18'
    assert meta['sections'] == {'spans': True, 'events': True,
                                'metrics': True, 'manifest': True}
    spans_doc = json.loads((Path(bundle) / 'spans.json').read_text())
    assert validate_events(spans_doc['traceEvents']) == []
    assert any(e.get('name') == 'model'
               for e in spans_doc['traceEvents'])
    lines = (Path(bundle) / 'events.jsonl').read_text().splitlines()
    assert any('pre-crash breadcrumb' in ln for ln in lines)
    assert json.loads((Path(bundle) / 'metrics.json').read_text()
                      )['queue']['depth'] == 1
    assert (Path(bundle) / 'metrics.prom').read_text() == 'vft_x 1\n'
    # broken collectors degrade to missing sections, never to a raise
    bb2, _ = _make_blackbox(
        tmp_path / 'b2',
        metrics_fn=lambda: (_ for _ in ()).throw(RuntimeError('wedged')))
    bundle2 = bb2.dump('watchdog_stall')
    assert bundle2 is not None and validate_bundle(bundle2) == []
    meta2 = json.loads((Path(bundle2) / 'meta.json').read_text())
    assert meta2['sections']['metrics'] is False


def test_blackbox_gc_keeps_newest_under_cap_and_rate_limits(tmp_path):
    bb, rec = _make_blackbox(tmp_path)
    # every bundle carries the same ~payload; cap to roughly 2 bundles
    first = bb.dump('r0')
    size = sum(f.stat().st_size
               for f in Path(first).rglob('*') if f.is_file())
    bb.max_bytes = int(size * 2.5)
    for i in range(1, 6):
        assert bb.dump(f'r{i}') is not None
    bundles = sorted(p.name for p in (tmp_path / 'postmortem').iterdir())
    total = sum(f.stat().st_size
                for f in (tmp_path / 'postmortem').rglob('*')
                if f.is_file())
    assert total <= bb.max_bytes
    assert any(b.endswith('-r5') for b in bundles)   # newest survives
    assert not any(b.endswith('-r0') for b in bundles)  # oldest GC'd
    # rate limit: back-to-back dumps collapse (r5 just fired)
    bb.min_interval_s = 60.0
    assert bb.dump('r6') is None
    assert bb.suppressed == 1
    bb._last_dump_t = 0.0            # interval elapsed → dumps resume
    assert bb.dump('r7') is not None


def test_serve_worker_crash_dumps_blackbox(tmp_path):
    """An induced serve-worker crash walks the REAL crash path: the
    entry retires, and a post-mortem bundle appears (after the recovery,
    never instead of it)."""
    from video_features_tpu.obs.blackbox import validate_bundle
    from video_features_tpu.serve.server import ExtractionServer, _Worker
    from video_features_tpu.utils.tracing import NULL_TRACER

    pm = tmp_path / 'postmortem'
    server = ExtractionServer(base_overrides={
        'postmortem_dir': str(pm),
        'watchdog_stall_s': 3600.0,      # armed, but must stay quiet
    })
    assert server.blackbox is not None and server.watchdog is not None
    try:
        class BoomEx:
            trace_out = None
            tracer = NULL_TRACER

            def extract_packed(self, feed, **kw):
                raise RuntimeError('scheduler-level boom')

            def finish_obs(self, export_trace=True):
                pass

        w = _Worker(server, key=('boom',), label='boom', extractor=BoomEx(),
                    idle_flush_s=0.01)
        w.start()
        w.thread.join(30)
        assert not w.thread.is_alive() and w.crashed
        bundles = list(pm.iterdir())
        assert len(bundles) == 1
        assert validate_bundle(str(bundles[0])) == []
        meta = json.loads((bundles[0] / 'meta.json').read_text())
        assert meta['reason'] == 'serve_worker_crash'
        assert meta['extra']['label'] == 'boom'
        # the armed-but-quiet watchdog ledger rides along in the bundle
        assert meta['extra']['watchdog']['enabled'] is True
        # the metrics document names the watchdog + events + trace view
        doc = server.metrics()
        assert doc['watchdog']['enabled'] is True
        assert doc['watchdog']['stalls_total'] == 0
        prom = server._prometheus(doc)
        assert 'vft_watchdog_enabled 1' in prom
        assert 'vft_events_total' in prom
    finally:
        server.drain(wait=True, grace_s=30)


# -- vft-flight: trace_view upgrades -----------------------------------------


def _flight_trace(tmp_path):
    """A two-trace document: trace A's chain (ingress→model overlapped
    by d2h), trace B a lone span, plus shared-batch trace_ids."""
    tid_a, tid_b = 'a' * 32, 'b' * 32
    events = [
        {'name': 'ingress', 'ph': 'X', 'ts': 0.0, 'dur': 100.0,
         'pid': 1, 'tid': 1,
         'args': {'trace_id': tid_a, 'span_id': '1' * 16}},
        {'name': 'model', 'ph': 'X', 'ts': 120.0, 'dur': 200.0,
         'pid': 1, 'tid': 1,
         'args': {'trace_ids': [tid_a, tid_b], 'videos': ['v']}},
        {'name': 'd2h', 'ph': 'X', 'ts': 200.0, 'dur': 60.0,
         'pid': 1, 'tid': 2,
         'args': {'trace_ids': [tid_a]}},      # overlaps model
        {'name': 'save', 'ph': 'X', 'ts': 340.0, 'dur': 50.0,
         'pid': 1, 'tid': 1,
         'args': {'trace_id': tid_a, 'span_id': '2' * 16}},
        {'name': 'other', 'ph': 'X', 'ts': 400.0, 'dur': 10.0,
         'pid': 1, 'tid': 1},
    ]
    p = tmp_path / 'flight.json'
    p.write_text(json.dumps({'traceEvents': events}))
    return p, tid_a, tid_b


def test_trace_view_trace_id_filter_and_critical_path(tmp_path, capsys):
    from tools.trace_view import critical_path, main as trace_view_main
    p, tid_a, tid_b = _flight_trace(tmp_path)
    assert trace_view_main([str(p)]) == 0
    out = capsys.readouterr().out
    # per-trace critical-path summaries appear for both traces
    assert f'trace {tid_a}:' in out and f'trace {tid_b}:' in out
    # filter: only trace A's events counted
    assert trace_view_main([str(p), '--trace-id', tid_a]) == 0
    out = capsys.readouterr().out
    assert '4/5 events' in out
    assert f'trace {tid_b}:' not in out
    # unknown id: valid document, empty filter, exit 0
    assert trace_view_main([str(p), '--trace-id', 'f' * 32]) == 0
    # critical path: ingress(100) + model(200) + save(50) — d2h overlaps
    # model and must NOT be double-counted into the chain
    events = json.loads(p.read_text())['traceEvents']
    spans_a = [e for e in events if (e.get('args') or {}).get('trace_id')
               == tid_a or tid_a in ((e.get('args') or {}
                                      ).get('trace_ids') or ())]
    total, chain = critical_path(spans_a)
    assert total == pytest.approx(350.0)
    assert [e['name'] for e in chain] == ['ingress', 'model', 'save']


def test_trace_view_rejects_trace_id_without_span_id(tmp_path, capsys):
    from tools.trace_view import main as trace_view_main
    bad = {'traceEvents': [
        {'name': 'x', 'ph': 'X', 'ts': 0.0, 'dur': 1.0, 'pid': 1,
         'tid': 1, 'args': {'trace_id': 'a' * 32}},   # no span_id
    ]}
    p = tmp_path / 'unpaired.json'
    p.write_text(json.dumps(bad))
    assert trace_view_main([str(p), '--quiet']) == 1
    assert 'trace_id without span_id' in capsys.readouterr().err
    # batch-level trace_ids (shared work) are exempt by design
    ok = {'traceEvents': [
        {'name': 'model', 'ph': 'X', 'ts': 0.0, 'dur': 1.0, 'pid': 1,
         'tid': 1, 'args': {'trace_ids': ['a' * 32]}},
    ]}
    p2 = tmp_path / 'paired.json'
    p2.write_text(json.dumps(ok))
    assert trace_view_main([str(p2), '--quiet']) == 0
