"""The flight recorder (obs/): span timeline, metrics registry,
Prometheus exposition, run manifest, structured error log — and the
contracts that pin their schemas.
"""
import json
import logging
import re
from pathlib import Path

import numpy as np
import pytest

from video_features_tpu.obs.metrics import (
    DEFAULT_BUCKETS, Histogram, MetricsRegistry,
)
from video_features_tpu.obs.spans import SpanRecorder
from video_features_tpu.utils.tracing import Tracer

from tools.make_sample_video import write_noise_clip as _write_clip  # noqa: E402
from tools.trace_view import validate_events  # noqa: E402


# -- span recorder -----------------------------------------------------------

def test_span_recorder_records_and_exports(tmp_path):
    rec = SpanRecorder(capacity=100)
    t0 = 1.0
    rec.span('decode', t0, t0 + 0.5, video='a.mp4')
    rec.instant('video_done', video='a.mp4', outcome='saved')
    events = rec.snapshot()
    spans = [e for e in events if e['ph'] == 'X']
    assert len(spans) == 1
    assert spans[0]['name'] == 'decode'
    assert spans[0]['args']['video'] == 'a.mp4'
    assert spans[0]['dur'] == pytest.approx(0.5e6)
    assert validate_events(events) == []

    out = tmp_path / 'trace.json'
    rec.export(str(out))
    doc = json.loads(out.read_text())
    assert isinstance(doc['traceEvents'], list)
    assert doc['otherData']['events_dropped'] == 0


def test_span_recorder_ring_buffer_drops_oldest():
    rec = SpanRecorder(capacity=4)
    for i in range(10):
        rec.span(f's{i}', float(i), float(i) + 0.1)
    assert rec.dropped == 6
    names = [e['name'] for e in rec.snapshot() if e['ph'] == 'X']
    assert names == ['s6', 's7', 's8', 's9']


def test_merge_traces_aligns_recorders_on_common_origin():
    """Recorders created at different times (serve workers built hours
    apart) share one CLOCK; the merged export must re-base everything to
    ONE origin so cross-worker ordering survives — each recorder's own
    snapshot re-bases to its own epoch."""
    from video_features_tpu.obs.spans import merge_traces
    a, b = SpanRecorder(capacity=8), SpanRecorder(capacity=8)
    a._t0, b._t0 = 100.0, 110.0            # b "built" 10s later
    a.span('a_span', 100.0, 100.5)
    b.span('b_span', 110.0, 110.5)
    # alone, each re-bases to its own epoch: both spans sit at ts=0
    assert [e['ts'] for e in a.snapshot() if e['ph'] == 'X'] == [0.0]
    assert [e['ts'] for e in b.snapshot() if e['ph'] == 'X'] == [0.0]
    merged = {e['name']: e for e in merge_traces([a, b])
              if e['ph'] == 'X'}
    assert merged['a_span']['ts'] == 0.0
    assert merged['b_span']['ts'] == pytest.approx(10e6)


def test_disabled_recorder_is_noop():
    rec = SpanRecorder(capacity=8, enabled=False)
    rec.span('x', 0.0, 1.0)
    rec.instant('y')
    assert [e for e in rec.snapshot() if e['ph'] != 'M'] == []


def test_tracer_feeds_recorder():
    """The stage table and the span timeline are two views over the SAME
    instrumentation sites: a tracer with a recorder attached both
    aggregates and appends span events, with attrs flowing through."""
    rec = SpanRecorder(capacity=100)
    t = Tracer(enabled=True, recorder=rec)
    with t.stage('model', video='v.mp4'):
        pass
    t.add('decode', 0.25, video='w.mp4')
    rep = t.report()
    assert rep['model']['count'] == 1 and rep['decode']['count'] == 1
    spans = {e['name']: e for e in rec.snapshot() if e['ph'] == 'X'}
    assert spans['model']['args']['video'] == 'v.mp4'
    assert spans['decode']['args']['video'] == 'w.mp4'
    assert spans['decode']['dur'] == pytest.approx(0.25e6, rel=1e-3)


def test_null_tracer_never_records():
    from video_features_tpu.utils.tracing import NULL_TRACER
    with NULL_TRACER.stage('x', video='v'):
        pass
    assert NULL_TRACER.report() == {}


# -- trace_view validation ---------------------------------------------------

def test_trace_view_rejects_violations(tmp_path):
    from tools.trace_view import main as trace_view_main
    bad = {'traceEvents': [
        {'name': 'a', 'ph': 'X', 'ts': 5.0, 'dur': 1.0, 'pid': 1, 'tid': 1},
        {'name': 'b', 'ph': 'X', 'ts': 2.0, 'dur': -1.0, 'pid': 1, 'tid': 1},
        {'name': 'c', 'ph': 'E', 'ts': 9.0, 'pid': 1, 'tid': 1},
        {'ph': 'X', 'ts': 1.0, 'pid': 1, 'tid': 1},
    ]}
    p = tmp_path / 'bad.json'
    p.write_text(json.dumps(bad))
    assert trace_view_main([str(p)]) == 1
    assert trace_view_main([str(tmp_path / 'missing.json')]) == 2


def test_trace_view_accepts_b_e_pairs(tmp_path):
    from tools.trace_view import main as trace_view_main
    good = {'traceEvents': [
        {'name': 'outer', 'ph': 'B', 'ts': 0.0, 'pid': 1, 'tid': 1},
        {'name': 'inner', 'ph': 'B', 'ts': 1.0, 'pid': 1, 'tid': 1},
        {'name': 'inner', 'ph': 'E', 'ts': 2.0, 'pid': 1, 'tid': 1},
        {'name': 'outer', 'ph': 'E', 'ts': 3.0, 'pid': 1, 'tid': 1},
    ]}
    p = tmp_path / 'good.json'
    p.write_text(json.dumps(good))
    assert trace_view_main([str(p), '--quiet']) == 0


# -- metrics registry + Prometheus exposition --------------------------------

_SAMPLE_RE = re.compile(
    r'^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[a-zA-Z_][a-zA-Z0-9_]*="[^"]*"'
    r'(,[a-zA-Z_][a-zA-Z0-9_]*="[^"]*")*\})? '
    r'(NaN|[+-]?Inf|[-+0-9.eE]+)$')


def assert_valid_prometheus(text: str) -> None:
    """Line-grammar check for the text exposition format 0.0.4."""
    assert text.endswith('\n')
    seen_type = {}
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith('# HELP ') or line.startswith('# TYPE '):
            parts = line.split(' ', 3)
            assert len(parts) >= 4 or parts[1] == 'TYPE', line
            if parts[1] == 'TYPE':
                seen_type[parts[2]] = parts[3]
            continue
        assert _SAMPLE_RE.match(line), f'bad sample line: {line!r}'
    assert seen_type, 'no TYPE lines'


def test_registry_counter_gauge_histogram_render():
    reg = MetricsRegistry()
    reg.counter('vft_requests_total', 'requests',
                labels={'outcome': 'completed'}).inc(3)
    reg.counter('vft_requests_total',
                labels={'outcome': 'failed'}).inc()
    reg.gauge('vft_queue_depth', 'queued videos').set(7)
    h = reg.histogram('vft_latency_seconds', 'latency',
                      buckets=(0.1, 1.0, 10.0))
    for v in (0.05, 0.5, 5.0, 50.0):
        h.observe(v)
    text = reg.render()
    assert_valid_prometheus(text)
    assert 'vft_requests_total{outcome="completed"} 3' in text
    assert 'vft_queue_depth 7' in text
    # cumulative buckets: 0.1→1, 1.0→2, 10→3, +Inf→4
    assert 'vft_latency_seconds_bucket{le="0.1"} 1' in text
    assert 'vft_latency_seconds_bucket{le="1"} 2' in text
    assert 'vft_latency_seconds_bucket{le="10"} 3' in text
    assert 'vft_latency_seconds_bucket{le="+Inf"} 4' in text
    assert 'vft_latency_seconds_count 4' in text
    assert 'vft_latency_seconds_sum 55.55' in text
    # re-registration returns the same series
    assert reg.gauge('vft_queue_depth').value == 7


def test_registry_rejects_type_conflicts_and_negative_inc():
    reg = MetricsRegistry()
    reg.counter('x_total')
    with pytest.raises(ValueError):
        reg.gauge('x_total')
    with pytest.raises(ValueError):
        reg.counter('y_total').inc(-1)


def test_histogram_default_buckets_cover_latency_range():
    h = Histogram()
    assert h.buckets == tuple(sorted(DEFAULT_BUCKETS))
    h.observe(0.0)
    assert h.snapshot()['buckets'][0][1] == 1


def test_prometheus_from_serve_doc():
    """The serve metrics document renders to valid Prometheus text with
    the queue depth, pool hit rate, cache hits, and latency histogram
    the acceptance criteria name."""
    from video_features_tpu.obs.metrics import MetricsRegistry
    from video_features_tpu.serve import metrics as metrics_mod

    reg = MetricsRegistry()
    stats = metrics_mod.RequestStats(registry=reg)
    stats.bump('submitted')
    stats.bump('completed')
    stats.observe_latency(0.2)
    doc = metrics_mod.build_metrics(
        started_at=0.0, queue_depth=3, queue_capacity=64, draining=False,
        pool_stats={'size': 1, 'capacity': 4, 'hits': 5, 'misses': 1,
                    'hit_rate': 5 / 6, 'evictions': 0, 'builds': 1},
        request_stats=stats,
        stage_reports={'i3d': {'model': {
            'count': 4, 'total_s': 2.0, 'mean_s': 0.5, 'max_s': 0.9,
            'first_s': 0.9, 'occupancy': 0.75, 'occ_valid': 12,
            'occ_capacity': 16}}},
        cache_stats={'caches': 1, 'entries': 2, 'bytes': 10, 'hits': 7,
                     'misses': 3, 'hit_rate': 0.7, 'puts': 2,
                     'evictions': 0, 'corrupt_evicted': 0,
                     'bytes_saved': 123})
    text = metrics_mod.prometheus_text(doc, reg)
    assert_valid_prometheus(text)
    for needle in ('vft_serve_queue_depth 3',
                   'vft_warm_pool_hit_rate',
                   'vft_cache_hits 7',
                   'vft_serve_request_latency_seconds_bucket',
                   'vft_serve_requests_total{outcome="completed"} 1',
                   'vft_stage_seconds{stage="model"} 2',
                   'vft_stage_occupancy{stage="model"} 0.75'):
        assert needle in text, f'{needle!r} missing from:\n{text}'


# -- structured event log ----------------------------------------------------

def _make_stub(tmp_path, on_extraction, fail=True):
    from video_features_tpu.extract.base import BaseExtractor

    class Stub(BaseExtractor):
        output_feat_keys = ['rgb']

        def extract(self, video_path):
            if fail:
                raise RuntimeError('decode exploded')
            return {'rgb': np.ones((2, 3), np.float32)}

    return Stub('stub', on_extraction, str(tmp_path / 'tmp'),
                str(tmp_path / 'out'), keep_tmp_files=False, device='cpu')


def test_error_log_keeps_print_mode_stdout_clean(tmp_path, capsys, caplog):
    """The fault-isolation error report must never interleave with the
    feature stream: stdout stays byte-clean, the structured record (video
    path + traceback) lands on the logging channel → stderr."""
    ex = _make_stub(tmp_path, 'print')
    with caplog.at_level(logging.WARNING, logger='video_features_tpu'):
        ex._extract('/videos/bad.mp4')          # must not raise
    captured = capsys.readouterr()
    assert captured.out == ''                   # byte-clean feature stream
    assert 'bad.mp4' in captured.err
    assert 'RuntimeError' in captured.err       # full traceback, stderr
    rec = next(r for r in caplog.records if getattr(r, 'video', None))
    assert rec.levelno == logging.WARNING
    assert rec.video == '/videos/bad.mp4'
    assert rec.exc_info is not None


def test_packed_device_step_error_goes_to_logger(tmp_path, capsys, caplog):
    """parallel/packing.py's device-step fault isolation reports through
    the same structured channel — batch videos named, stdout untouched."""
    from video_features_tpu.obs.events import log_batch_error
    with caplog.at_level(logging.WARNING, logger='video_features_tpu'):
        try:
            raise RuntimeError('geometry will not compile')
        except RuntimeError:
            log_batch_error(['a.mp4', 'b.mp4'], valid=3, batch=4)
    captured = capsys.readouterr()
    assert captured.out == ''
    assert 'a.mp4' in captured.err and 'geometry will not compile' in captured.err
    rec = next(r for r in caplog.records if getattr(r, 'videos', None))
    assert rec.valid == 3 and rec.batch == 4


# -- the packed CLI run: trace + manifest end to end -------------------------

@pytest.fixture(scope='module')
def obs_worklist(tmp_path_factory):
    d = tmp_path_factory.mktemp('obsvids')
    return [str(_write_clip(d / f'v{i}.mp4', n, seed=10 + i))
            for i, n in enumerate((6, 9))]


def test_packed_cli_trace_out_covers_every_video(obs_worklist, tmp_path,
                                                 capsys):
    """Acceptance: one packed CLI run with trace_out yields a Chrome
    trace whose spans cover decode/pack/device-step/save for EVERY video
    in the worklist, and tools/trace_view.py validates it."""
    from tools.trace_view import main as trace_view_main
    from video_features_tpu.cli import main

    trace = tmp_path / 'trace.json'
    manifest = tmp_path / 'manifest.json'
    rc = main([
        'feature_type=resnet', 'model_name=resnet18', 'device=cpu',
        f'video_paths=[{",".join(obs_worklist)}]',
        'pack_across_videos=true', 'batch_size=4',
        'allow_random_weights=true', 'on_extraction=save_numpy',
        f'output_path={tmp_path / "out"}', f'tmp_path={tmp_path / "tmp"}',
        f'trace_out={trace}', f'manifest_out={manifest}'])
    assert rc == 0
    capsys.readouterr()

    doc = json.loads(trace.read_text())
    events = doc['traceEvents']
    assert validate_events(events) == []
    spans = [e for e in events if e['ph'] == 'X']
    by_name = {}
    for e in spans:
        by_name.setdefault(e['name'], []).append(e)
    for path in obs_worklist:
        assert any(e['args'].get('video') == path
                   for e in by_name.get('decode+preprocess', [])
                   if 'args' in e), f'no decode span for {path}'
        assert any(path in e['args'].get('videos', [])
                   for e in by_name.get('pack', []) if 'args' in e), \
            f'no pack span for {path}'
        assert any(path in e['args'].get('videos', [])
                   for e in by_name.get('model', []) if 'args' in e), \
            f'no device-step span for {path}'
        # the deferred readback is its own stage with the same
        # provenance/occupancy attrs — the timeline must show model
        # (dispatch+compute) and d2h (readback) as DISTINCT spans
        assert any(path in e['args'].get('videos', [])
                   and e['args'].get('capacity')
                   for e in by_name.get('d2h', []) if 'args' in e), \
            f'no d2h span for {path}'
        assert any(e['args'].get('video') == path
                   for e in by_name.get('save', []) if 'args' in e), \
            f'no save span for {path}'
    # no time lost or double-counted: every dispatched batch has exactly
    # one model span and one d2h span
    assert len(by_name.get('d2h', [])) == len(by_name.get('model', []))
    # the validator tool accepts the real artifact (tier-1 exercise)
    assert trace_view_main([str(trace), '--quiet']) == 0
    capsys.readouterr()

    # -- run manifest: fingerprints + outcomes + stages ----------------------
    man = json.loads(manifest.read_text())
    assert man['schema'] == 'video_features_tpu.run_manifest/1'
    assert man['fingerprints']['run']
    assert man['fingerprints']['config']
    assert set(man['videos']) == set(obs_worklist)
    assert all(v['outcome'] == 'saved' for v in man['videos'].values())
    assert man['outcomes'] == {'saved': len(obs_worklist)}
    assert 'model' in man['stages'] and man['stages']['model']['count'] > 0
    assert man['config']['feature_type'] == 'resnet'
    # outputs written normally alongside the telemetry
    from video_features_tpu.utils.output import make_path
    for p in obs_worklist:
        arr = np.load(make_path(str(tmp_path / 'out' / 'resnet' /
                                    'resnet18'), p, 'resnet', '.npy'))
        assert arr.shape[1] == 512


def test_one_shot_cli_trace_and_manifest(obs_worklist, tmp_path, capsys):
    """The per-video loop records the same telemetry: a video span per
    clip plus the stage spans, and a manifest with per-video outcomes."""
    from video_features_tpu.cli import main

    trace = tmp_path / 'trace.json'
    manifest = tmp_path / 'manifest.json'
    rc = main([
        'feature_type=resnet', 'model_name=resnet18', 'device=cpu',
        f'video_paths=[{",".join(obs_worklist)}]', 'batch_size=4',
        'allow_random_weights=true', 'on_extraction=save_numpy',
        f'output_path={tmp_path / "out"}', f'tmp_path={tmp_path / "tmp"}',
        f'trace_out={trace}', f'manifest_out={manifest}'])
    assert rc == 0
    capsys.readouterr()
    events = json.loads(trace.read_text())['traceEvents']
    assert validate_events(events) == []
    vids = [e for e in events if e['ph'] == 'X' and e['name'] == 'video']
    assert {e['args']['video'] for e in vids} == set(obs_worklist)
    assert all(e['args']['outcome'] == 'saved' for e in vids)
    man = json.loads(manifest.read_text())
    assert man['outcomes'] == {'saved': len(obs_worklist)}
    assert man['stages']                       # folded across the reset


# -- serve: Prometheus endpoint + file mirror --------------------------------

def test_serve_prometheus_endpoint_and_mirror(tmp_path):
    from video_features_tpu.serve.client import ServeClient
    from video_features_tpu.serve.server import ExtractionServer

    metrics_path = str(tmp_path / 'metrics.json')
    server = ExtractionServer(metrics_path=metrics_path).start()
    try:
        client = ServeClient(port=server.port)
        text = client.metrics_prom()
        assert_valid_prometheus(text)
        for needle in ('vft_serve_queue_depth 0',
                       'vft_serve_queue_capacity 64',
                       'vft_warm_pool_hit_rate',
                       'vft_cache_hits',
                       'vft_inflight_batches 0',
                       'vft_serve_request_latency_seconds_count',
                       'vft_serve_uptime_seconds'):
            assert needle in text, f'{needle!r} missing from:\n{text}'
    finally:
        server.drain(wait=True, grace_s=30)
    # the atomic mirror wrote BOTH formats on drain
    doc = json.loads(Path(metrics_path).read_text())
    assert 'queue' in doc
    prom = Path(metrics_path + '.prom').read_text()
    assert_valid_prometheus(prom)
    assert 'vft_serve_draining 1' in prom


def test_serve_drain_exports_merged_trace(obs_worklist, tmp_path):
    """A server-wide trace_out base override stitches EVERY worker's
    recorder into one Chrome trace at drain — spans from a real request
    (decode/pack/model/save, request ids) survive the merge and the
    export validates."""
    from video_features_tpu.serve.client import ServeClient
    from video_features_tpu.serve.server import ExtractionServer

    trace = tmp_path / 'serve_trace.json'
    server = ExtractionServer(base_overrides={
        'device': 'cpu', 'model_name': 'resnet18', 'batch_size': 4,
        'allow_random_weights': True, 'on_extraction': 'save_numpy',
        'tmp_path': str(tmp_path / 'serve_tmp'),
        'output_path': str(tmp_path / 'serve_out'),
        'trace_out': str(trace),
    }, queue_depth=8, pool_size=2).start()
    try:
        client = ServeClient(port=server.port)
        rid = client.submit('resnet', [obs_worklist[0]])
        st = client.wait(rid, timeout_s=300)
        assert st['state'] == 'done', st
    finally:
        server.drain(wait=True, grace_s=120)

    doc = json.loads(trace.read_text())
    events = doc['traceEvents']
    assert validate_events(events) == []
    assert doc['otherData']['recorders_merged'] >= 1
    spans = [e for e in events if e['ph'] == 'X' and 'args' in e]
    assert any(e['name'] == 'model' for e in spans)
    assert any(e['name'] == 'save'
               and e['args'].get('video') == obs_worklist[0]
               and e['args'].get('request_id') == rid for e in spans)


# -- bench_diff --------------------------------------------------------------

def _bench_rec(**rungs):
    return {'metric': 'm', 'value': rungs.get('value', 1.0), 'unit': 'u',
            'vs_baseline': 1.0, 'rungs': rungs}


def test_bench_diff_detects_direction_aware_regressions(tmp_path, capsys):
    from tools.bench_diff import main as bench_diff_main
    old = tmp_path / 'old.json'
    new = tmp_path / 'new.json'
    old.write_text(json.dumps(_bench_rec(
        e2e_mixed=10.0, serve_p99_latency_s=1.0, only_old=5.0)))
    # throughput dropped 50% AND latency doubled: both are regressions
    new.write_text(json.dumps(_bench_rec(
        e2e_mixed=5.0, serve_p99_latency_s=2.0, only_new='err')))
    assert bench_diff_main([str(old), str(new)]) == 0   # report-only mode
    capsys.readouterr()
    assert bench_diff_main([str(old), str(new),
                            '--fail-on-regression', '10']) == 1
    err = capsys.readouterr().err
    assert 'e2e_mixed' in err and 'serve_p99_latency_s' in err

    # within threshold → pass
    new.write_text(json.dumps(_bench_rec(
        e2e_mixed=9.8, serve_p99_latency_s=1.02)))
    assert bench_diff_main([str(old), str(new),
                            '--fail-on-regression', '10']) == 0
    assert bench_diff_main([str(tmp_path / 'nope.json'), str(new)]) == 2


def test_bench_diff_latency_improvement_is_not_regression(tmp_path):
    from tools.bench_diff import main as bench_diff_main
    old = tmp_path / 'o.json'
    new = tmp_path / 'n.json'
    old.write_text(json.dumps(_bench_rec(serve_p50_latency_s=2.0)))
    new.write_text(json.dumps(_bench_rec(serve_p50_latency_s=0.5)))
    assert bench_diff_main([str(old), str(new),
                            '--fail-on-regression', '1']) == 0


# -- schema contracts --------------------------------------------------------

TRACER_RECORD_KEYS = {'count', 'total_s', 'mean_s', 'max_s', 'first_s',
                      'ramp', 'occupancy', 'occ_valid', 'occ_capacity',
                      # mesh-sharded batches: per-device slot ledger
                      'occ_device'}
METRICS_DOC_KEYS = {'uptime_s', 'queue', 'warm_pool', 'cache', 'farm',
                    'requests', 'latency', 'stages', 'stages_merged',
                    'inflight_batches',
                    # network front door (ingress/): per-tenant view,
                    # {'enabled': False, ...} on loopback-only servers
                    'ingress'}
TRACE_EVENT_KEYS = {'name', 'ph', 'ts', 'dur', 'pid', 'tid', 'args', 's'}
MANIFEST_KEYS = {'schema', 'version', 'started_at_unix_s', 'wall_s',
                 'config', 'fingerprints', 'videos', 'outcomes', 'stages',
                 'compile', 'executables', 'farm', 'mesh', 'ingress',
                 'programs_lock'}


CANONICAL_STAGES = {'decode', 'decode+preprocess', 'audio_dsp',
                    'queue_idle', 'pack', 'h2d', 'model', 'd2h', 'save',
                    'cache_lookup', 'cache_publish'}


def test_stage_vocabulary_contract():
    """Pin the canonical stage names (utils.tracing.STAGES): dashboards
    key vft_stage_* families and bench stage_reports on them — renaming
    or dropping one (e.g. folding d2h back into model) must be an
    intentional, test-visible event."""
    from video_features_tpu.utils.tracing import STAGES
    assert set(STAGES) == CANONICAL_STAGES
    assert 'model' in STAGES and 'd2h' in STAGES    # split, not aliased


def test_merge_reports_keeps_model_and_d2h_distinct():
    """Fleet-wide merges (serve metrics, retired-worker history) must
    keep the dispatch and readback stages separate — their shares sum to
    the old all-in 'model' share, so folding them would re-launder
    readback into compute."""
    from video_features_tpu.utils.tracing import merge_reports
    a = {'model': {'count': 2, 'total_s': 1.0, 'max_s': 0.6,
                   'first_s': 0.6},
         'd2h': {'count': 2, 'total_s': 0.5, 'max_s': 0.3, 'first_s': 0.3,
                 'occ_valid': 6, 'occ_capacity': 8}}
    b = {'model': {'count': 1, 'total_s': 0.4, 'max_s': 0.4,
                   'first_s': 0.4},
         'd2h': {'count': 1, 'total_s': 0.1, 'max_s': 0.1, 'first_s': 0.1,
                 'occ_valid': 4, 'occ_capacity': 4}}
    merged = merge_reports([a, b])
    assert merged['model']['total_s'] == pytest.approx(1.4)
    assert merged['d2h']['total_s'] == pytest.approx(0.6)
    assert merged['d2h']['occupancy'] == pytest.approx(10 / 12)


def test_schema_contract_key_sets(tmp_path):
    """Pin the three export schemas: a key rename is an intentional,
    test-visible event — scrapers and dashboards depend on these."""
    # tracer report records
    t = Tracer()
    with t.stage('a'):
        pass
    with t.stage('a'):
        pass
    t.add_occupancy('a', 3, 4)
    rec = t.report()['a']
    assert set(rec) <= TRACER_RECORD_KEYS
    assert {'count', 'total_s', 'mean_s', 'max_s', 'first_s'} <= set(rec)

    # serve metrics document
    from video_features_tpu.serve import metrics as metrics_mod
    doc = metrics_mod.build_metrics(
        started_at=0.0, queue_depth=0, queue_capacity=1, draining=False,
        pool_stats={}, request_stats=metrics_mod.RequestStats(),
        stage_reports={})
    assert set(doc) == METRICS_DOC_KEYS
    assert set(doc['requests']) == {'submitted', 'completed', 'failed',
                                    'rejected', 'expired_videos',
                                    'cached_videos'}

    # trace events
    sr = SpanRecorder(capacity=8)
    sr.span('s', 0.0, 1.0, video='v')
    sr.instant('i')
    for ev in sr.snapshot():
        assert set(ev) <= TRACE_EVENT_KEYS, ev

    # run manifest
    from video_features_tpu.obs.manifest import RunManifest
    man = RunManifest({'feature_type': 'resnet'}).document()
    assert set(man) == MANIFEST_KEYS
