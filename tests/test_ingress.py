"""The ingress (ingress/): network front door over the serve daemon.

Covers the four tentpole pieces — transport (framing bounds, chunked
streaming, drain/reap), tenancy (API keys, token-bucket + concurrency
quotas, priority shed), segment queries (range plumbed through the
windower + cache key; byte parity vs the loopback path; decode bounded
to the covered range, tracer-verified), live sessions (per-window
streamed chunks, duplicate-id rejection, drain reaping) — plus the
loopback satellites (protocol ``v`` versioning, client connect retry).

The e2e layer runs resnet18 random weights on CPU against noise-clip
fixtures, one shared server per module (same policy as test_serve.py).
"""
import io
import json
import socket
import threading
import time
from pathlib import Path

import numpy as np
import pytest

from tools.make_sample_video import write_noise_clip as _write_clip
from video_features_tpu.utils.output import make_path

API_KEY = 'test-key-interactive'
BATCH_KEY = 'test-key-batch'
LIMITED_KEY = 'test-key-limited'


# -- pure units (no server, no jax) ------------------------------------------

def test_token_bucket_and_quota_manager():
    from video_features_tpu.ingress.auth import Tenant
    from video_features_tpu.ingress.quota import QuotaManager, TokenBucket

    assert TokenBucket(None, 1).try_acquire()       # unlimited

    q = QuotaManager()
    slow = Tenant('slow', rate_rps=0.001, burst=2)
    assert q.acquire(slow) == (True, None)
    assert q.acquire(slow) == (True, None)
    ok, reason = q.acquire(slow)
    assert (ok, reason) == (False, 'rate_limited')  # bucket dry

    one = Tenant('one', max_concurrent=1)
    assert q.acquire(one) == (True, None)
    assert q.acquire(one) == (False, 'concurrency')
    q.release('one')
    assert q.acquire(one) == (True, None)

    snap = q.snapshot()
    assert snap['slow']['shed'] == 1 and snap['one']['shed'] == 1
    assert snap['one']['inflight'] == 1


def test_auth_file_parsing_and_header_auth(tmp_path):
    from video_features_tpu.ingress.auth import ApiKeyAuth

    p = tmp_path / 'keys.json'
    p.write_text(json.dumps({'keys': {
        'k1': {'tenant': 'acme', 'priority': 'batch', 'rate_rps': 10},
        'k2': {'tenant': 'acme', 'priority': 'batch', 'rate_rps': 10},
        'k3': {'tenant': 'zeta', 'max_concurrent': 2},
    }}))
    auth = ApiKeyAuth.from_file(str(p))
    assert auth.n_tenants == 2                    # two keys share 'acme'
    t = auth.authenticate({'authorization': 'Bearer k1'})
    assert t.name == 'acme' and t.priority == 'batch'
    assert auth.authenticate({'x-api-key': 'k3'}).name == 'zeta'
    assert auth.authenticate({'authorization': 'Bearer nope'}) is None
    assert auth.authenticate({}) is None

    # keys sharing a tenant share its quota ledger: their policies must
    # agree, or the effective policy would be first-authenticated-wins
    bad = tmp_path / 'conflict.json'
    bad.write_text(json.dumps({'keys': {
        'kA': {'tenant': 'acme', 'rate_rps': 5},
        'kB': {'tenant': 'acme', 'rate_rps': 500},
    }}))
    with pytest.raises(ValueError, match='conflicting policies'):
        ApiKeyAuth.from_file(str(bad))

    bad = tmp_path / 'bad.json'
    bad.write_text(json.dumps({'keys': {'k': {'priority': 'interactive'}}}))
    with pytest.raises(ValueError, match='no tenant'):
        ApiKeyAuth.from_file(str(bad))
    bad.write_text(json.dumps(
        {'keys': {'k': {'tenant': 't', 'shoe_size': 9}}}))
    with pytest.raises(ValueError, match='unknown fields'):
        ApiKeyAuth.from_file(str(bad))


def test_http_oversized_body_is_structured_413():
    """An oversized DECLARED body must come back as a structured 413 —
    before a byte of the payload is read — and an oversized chunk must
    do the same mid-stream; neither may crash the reader."""
    from video_features_tpu.ingress.http import HttpError, HttpServer

    def handler(req, resp, conn):
        if req.chunked:
            for _ in req.iter_chunks(max_chunk_bytes=64):
                pass
            resp.send_json(200, {'ok': True})
        else:
            req.read_body(max_bytes=128)
            resp.send_json(200, {'ok': True})

    srv = HttpServer(handler).start()
    try:
        import http.client
        c = http.client.HTTPConnection('127.0.0.1', srv.port, timeout=10)
        c.request('POST', '/x', body=b'y' * 1024)
        r = c.getresponse()
        body = json.loads(r.read())
        assert r.status == 413 and body['error'] == 'body_too_large'
        assert body['max_bytes'] == 128 and body['got_bytes'] == 1024

        s = socket.create_connection(('127.0.0.1', srv.port), timeout=10)
        s.sendall(b'POST /x HTTP/1.1\r\nHost: a\r\n'
                  b'Transfer-Encoding: chunked\r\n\r\n')
        s.sendall(b'%x\r\n%s\r\n' % (4096, b'z' * 4096))
        resp = s.makefile('rb').read()
        assert b'413' in resp.split(b'\r\n', 1)[0]
        assert b'body_too_large' in resp

        # a NEGATIVE chunk size must be a structured 400, never an
        # unbounded read-to-EOF (int(_, 16) parses '-1'; rfile.read(-1)
        # would buffer everything the client cares to send)
        s2 = socket.create_connection(('127.0.0.1', srv.port), timeout=10)
        s2.sendall(b'POST /x HTTP/1.1\r\nHost: a\r\n'
                   b'Transfer-Encoding: chunked\r\n\r\n'
                   b'-1\r\n' + b'y' * 1024)
        resp2 = s2.makefile('rb').read()
        assert b'400' in resp2.split(b'\r\n', 1)[0]
        assert b'negative chunk size' in resp2
    finally:
        srv.begin_drain()
        srv.finish_drain(grace_s=1.0)


def test_segment_name_and_cache_key_distinctness(tmp_path):
    from video_features_tpu.cache.key import video_cache_key
    from video_features_tpu.parallel.packing import VideoTask, segment_name

    clip = tmp_path / 'a.mp4'
    clip.write_bytes(b'notavideo but hashable')
    assert segment_name(str(clip), None) == str(clip)
    named = segment_name(str(clip), (1.5, 3.0))
    assert named.endswith('a_seg1500-3000ms.mp4')
    # millisecond quantization: float jitter below 1ms can't fork names
    assert segment_name(str(clip), (1.5000001, 3.0)) == named

    full = video_cache_key(str(clip), 'fp')
    seg = video_cache_key(str(clip), 'fp', segment=(1.5, 3.0))
    seg2 = video_cache_key(str(clip), 'fp', segment=(1.5, 4.0))
    assert len({full, seg, seg2}) == 3   # never collide with full/other

    t = VideoTask(str(clip), segment=(1.5, 3.0))
    assert t.name_path == named
    assert VideoTask(str(clip)).name_path == str(clip)


def test_stream_windows_frame_range_bounds_decode():
    """The windower emits exactly the range-overlapping windows and
    stops PULLING decode batches past the range's end — the unit behind
    the 'decode proportional to the range' acceptance."""
    from video_features_tpu.extract.streaming import stream_windows

    frames = [np.full((2, 2), i, np.uint8) for i in range(100)]

    class Counting:
        def __init__(self):
            self.pulled = 0

        def __iter__(self):
            for i in range(0, 100, 8):
                self.pulled += 1
                yield frames[i:i + 8], None, None

    full_src = Counting()
    full = list(stream_windows(iter(full_src), 4, 2))
    assert len(full) == 49

    src = Counting()
    seg = list(stream_windows(iter(src), 4, 2, frame_range=(10, 20)))
    # windows overlapping [10, 20): starts 8..18
    assert [int(w[0, 0, 0]) for w in seg] == [8, 10, 12, 14, 16, 18]
    # byte-identical to the same windows of the full run
    for w in seg:
        assert np.array_equal(w, full[int(w[0, 0, 0]) // 2])
    # decode stopped early: batches pulled ∝ range end, not video length
    assert src.pulled < full_src.pulled
    assert src.pulled <= 3

    empty = Counting()
    assert list(stream_windows(iter(empty), 4, 2, frame_range=(5, 5))) == []


def test_live_session_windowing_unit():
    """LiveSession.windows replays stack windowing over pushed frames
    and yields FLUSH on arrival lulls."""
    from video_features_tpu.ingress.live import LiveSession
    from video_features_tpu.parallel.packing import FLUSH

    class StubEx:
        feature_type = 'stub'

        def live_window_spec(self):
            return (4, 2, None, False)

    s = LiveSession('s1', 'acme', fps=10.0, idle_flush_s=0.01)
    gen = s.windows(StubEx())
    # nothing pushed yet → the first item is a lull FLUSH
    assert next(gen) is FLUSH
    frames = np.arange(10, dtype=np.uint8).reshape(10, 1, 1, 1) * \
        np.ones((1, 2, 2, 3), np.uint8)
    s.push(frames[:6])
    s.push(frames[6:])
    s.end_input()
    got = [item for item in gen if item is not FLUSH]
    # starts 0,2,4,6 (win=4 over 10 frames)
    assert [int(w[0, 0, 0, 0]) for w, _ in got] == [0, 2, 4, 6]
    assert s.windows_in == 4

    # framewise spec: per-frame windows with synthesized timestamps
    class StubFrameEx:
        feature_type = 'stubf'

        def live_window_spec(self):
            return (1, 1, None, True)

    s2 = LiveSession('s2', 'acme', fps=10.0, idle_flush_s=0.01)
    s2.push(frames[:3])
    s2.end_input()
    got2 = [item for item in s2.windows(StubFrameEx())
            if item is not FLUSH]
    assert [m for _, m in got2] == [0.0, 100.0, 200.0]


def test_decode_frame_chunk_validation():
    from video_features_tpu.ingress.live import (
        LiveSessionError, decode_frame_chunk,
    )
    buf = io.BytesIO()
    np.save(buf, np.zeros((2, 4, 4, 3), np.uint8))
    assert decode_frame_chunk(buf.getvalue()).shape == (2, 4, 4, 3)
    buf = io.BytesIO()
    np.save(buf, np.zeros((4, 4, 3), np.uint8))      # single HWC frame
    assert decode_frame_chunk(buf.getvalue()).shape == (1, 4, 4, 3)
    with pytest.raises(LiveSessionError, match='undecodable'):
        decode_frame_chunk(b'not npy')
    buf = io.BytesIO()
    np.save(buf, np.zeros((4, 4, 3), np.float32))
    with pytest.raises(LiveSessionError, match='uint8'):
        decode_frame_chunk(buf.getvalue())


def test_protocol_version_check_unit():
    from video_features_tpu.serve import protocol

    assert protocol.check_version({'cmd': 'ping'}) is None
    assert protocol.check_version({'v': '1.0'}) is None
    assert protocol.check_version({'v': '1.7'}) is None   # minor skew ok
    err = protocol.check_version({'v': '99.0', 'request_id': 'r42'})
    assert err['ok'] is False and 'unsupported protocol' in err['error']
    assert err['request_id'] == 'r42' and err['v'] == protocol.VERSION
    err = protocol.check_version({'v': 'abc'})
    assert err['ok'] is False and 'malformed' in err['error']


def test_client_connect_retries_until_late_binding_listener():
    """A refused connect retries with backoff up to the deadline — a
    listener that binds 0.4s late is cured, a dead port still fails."""
    from video_features_tpu.serve import protocol
    from video_features_tpu.serve.client import ServeClient

    probe = socket.socket()
    probe.bind(('127.0.0.1', 0))
    port = probe.getsockname()[1]
    probe.close()                           # port now refuses connects

    def late_listener():
        time.sleep(0.4)
        srv = socket.socket()
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind(('127.0.0.1', port))
        srv.listen(1)
        conn, _ = srv.accept()
        with conn, conn.makefile('rb') as rf:
            msg = protocol.decode(rf.readline())
            assert msg['cmd'] == 'ping' and msg['v'] == protocol.VERSION
            conn.sendall(protocol.encode(protocol.ok(draining=False)))
        srv.close()

    t = threading.Thread(target=late_listener, daemon=True)
    t.start()
    assert ServeClient(port, connect_timeout_s=10.0).ping()
    t.join(5.0)

    probe = socket.socket()
    probe.bind(('127.0.0.1', 0))
    dead = probe.getsockname()[1]
    probe.close()
    t0 = time.monotonic()
    with pytest.raises((ConnectionRefusedError, OSError)):
        ServeClient(dead, connect_timeout_s=0.3).ping()
    assert time.monotonic() - t0 < 5.0      # bounded, no infinite retry


# -- e2e: one shared server + gateway (resnet18 random weights, CPU) ---------

@pytest.fixture(scope='module')
def ingress_clips(tmp_path_factory):
    d = tmp_path_factory.mktemp('ingressvids')
    return [str(_write_clip(d / f'iv{i}.mp4', n, seed=10 + i))
            for i, n in enumerate((16, 6))]


def _base_overrides(root: Path):
    return {
        'device': 'cpu', 'model_name': 'resnet18', 'batch_size': 4,
        'allow_random_weights': True, 'on_extraction': 'save_numpy',
        'tmp_path': str(root / 'ing_tmp'),
        'output_path': str(root / 'ing_out_default'),
    }


def _make_auth():
    from video_features_tpu.ingress.auth import ApiKeyAuth, Tenant
    return ApiKeyAuth({
        API_KEY: Tenant('acme'),
        BATCH_KEY: Tenant('bulkco', priority='batch'),
        LIMITED_KEY: Tenant('capped', rate_rps=0.001, burst=1,
                            max_concurrent=1),
    })


@pytest.fixture(scope='module')
def gatewayed(tmp_path_factory):
    from video_features_tpu.ingress.gateway import IngressGateway
    from video_features_tpu.serve.server import ExtractionServer
    root = tmp_path_factory.mktemp('ingress_srv')
    server = ExtractionServer(base_overrides=_base_overrides(root),
                              queue_depth=8, pool_size=2,
                              batch_shed_fraction=0.5).start()
    gateway = IngressGateway(server, auth=_make_auth()).start()
    yield server, gateway, root
    server.drain(wait=True, grace_s=120)


def _api(gateway, method, path, body=None, key=API_KEY, timeout=180,
         headers=None):
    import http.client
    c = http.client.HTTPConnection('127.0.0.1', gateway.port,
                                   timeout=timeout)
    headers = dict(headers or {})
    if key:
        headers['Authorization'] = f'Bearer {key}'
    c.request(method, path,
              body=json.dumps(body) if body is not None else None,
              headers=headers)
    r = c.getresponse()
    raw = r.read()
    c.close()
    try:
        return r.status, json.loads(raw)
    except ValueError:
        return r.status, raw


def _wait_done(gateway, rid, key=API_KEY, timeout_s=180.0):
    deadline = time.monotonic() + timeout_s
    while True:
        st, doc = _api(gateway, 'GET', f'/v1/requests/{rid}', key=key)
        assert st == 200, doc
        if doc['state'] != 'running':
            return doc
        assert time.monotonic() < deadline, f'request {rid} stuck: {doc}'
        time.sleep(0.1)


def test_health_auth_and_metrics_surfaces(gatewayed):
    server, gateway, _ = gatewayed
    st, doc = _api(gateway, 'GET', '/healthz', key=None)
    assert st == 200 and doc['ok'] and doc['draining'] is False
    st, doc = _api(gateway, 'GET', '/v1/metrics', key='wrong-key')
    assert st == 401 and doc['error'] == 'unauthorized'
    st, doc = _api(gateway, 'GET', '/v1/metrics')
    assert st == 200 and doc['metrics']['ingress']['enabled'] is True
    st, text = _api(gateway, 'GET', '/metrics')
    assert st == 200 and b'vft_ingress_requests_total' in text
    st, doc = _api(gateway, 'GET', '/v1/nope')
    assert st == 404


def test_segment_query_parity_ingress_vs_loopback(gatewayed, ingress_clips):
    """The acceptance triangle: the same [0.2, 0.6) range over ingress
    and over the loopback socket produce byte-identical feature files,
    named so they can never collide with a full extraction."""
    from video_features_tpu.serve.client import ServeClient
    server, gateway, root = gatewayed
    clip = ingress_clips[0]
    seg = [0.2, 0.6]

    out_ing = str(root / 'seg_ing')
    st, doc = _api(gateway, 'POST', '/v1/extract', {
        'feature_type': 'resnet', 'video_paths': [clip], 'range': seg,
        'overrides': {'output_path': out_ing}})
    assert st == 200 and doc['tenant'] == 'acme', doc
    status = _wait_done(gateway, doc['request_id'])
    assert status['state'] == 'done' and status['range'] == seg
    assert status['tenant'] == 'acme'

    out_loop = str(root / 'seg_loop')
    client = ServeClient(port=server.port)
    rid = client.submit('resnet', [clip],
                        overrides={'output_path': out_loop}, range_s=seg)
    assert client.wait(rid, timeout_s=180)['state'] == 'done'

    stem = Path(clip).stem + '_seg200-600ms.mp4'
    for key_, ext in (('resnet', '.npy'), ('timestamps_ms', '.npy')):
        a = Path(make_path(str(Path(out_ing) / 'resnet' / 'resnet18'),
                           stem, key_, ext)).read_bytes()
        b = Path(make_path(str(Path(out_loop) / 'resnet' / 'resnet18'),
                           stem, key_, ext)).read_bytes()
        assert a == b, f'{key_} differs between ingress and loopback'
    ts = np.load(make_path(str(Path(out_ing) / 'resnet' / 'resnet18'),
                           stem, 'timestamps_ms', '.npy'))
    # 25 fps clip → frames 5..14 → timestamps 200..560 ms: the covered
    # range only, not the whole video
    assert ts.min() >= 200.0 - 1e-6 and ts.max() < 600.0
    assert 0 < len(ts) < 16


def test_trace_route_tenant_scoped_and_traceparent_adopted(gatewayed,
                                                           ingress_clips):
    """vft-flight over the front door: the caller's W3C traceparent is
    adopted end-to-end (echoed as trace_id), GET /v1/requests/<id>/trace
    answers the OWNING tenant, a FOREIGN tenant gets an explicit 403,
    and an unknown id stays 404."""
    server, gateway, root = gatewayed
    clip = ingress_clips[1]
    caller_trace = 'feedc0de' * 4
    st, doc = _api(gateway, 'POST', '/v1/extract', {
        'feature_type': 'resnet', 'video_paths': [clip],
        'overrides': {'output_path': str(root / 'trace_out_dir')}},
        headers={'traceparent':
                 f'00-{caller_trace}-00f067aa0ba902b7-01'})
    assert st == 200, doc
    assert doc['trace_id'] == caller_trace, doc
    rid = doc['request_id']
    assert _wait_done(gateway, rid)['state'] == 'done'

    # owner reads its trace (this server runs without trace_out, so the
    # assembled event list is empty — the scoping contract is the point)
    st, tr = _api(gateway, 'GET', f'/v1/requests/{rid}/trace')
    assert st == 200, tr
    assert tr['trace_id'] == caller_trace and tr['tenant'] == 'acme'
    assert tr['request_id'] == rid and isinstance(tr['events'], list)

    # a FOREIGN tenant gets an explicit 403 (not status's 404 ambiguity)
    st, err = _api(gateway, 'GET', f'/v1/requests/{rid}/trace',
                   key=BATCH_KEY)
    assert st == 403 and err['error'] == 'forbidden', err
    # ...while the same foreign tenant's STATUS read stays a 404
    st, err = _api(gateway, 'GET', f'/v1/requests/{rid}', key=BATCH_KEY)
    assert st == 404
    # unknown id: 404 for everyone
    st, err = _api(gateway, 'GET', '/v1/requests/r999999/trace')
    assert st == 404
    # a malformed traceparent degrades to a minted trace, never a reject
    st, doc2 = _api(gateway, 'POST', '/v1/extract', {
        'feature_type': 'resnet', 'video_paths': [clip],
        'overrides': {'output_path': str(root / 'trace_out_dir2')}},
        headers={'traceparent': 'garbage'})
    assert st == 200 and len(doc2['trace_id']) == 32
    assert doc2['trace_id'] != caller_trace
    _wait_done(gateway, doc2['request_id'])


def test_segment_decode_is_tracer_bounded_to_range(ingress_clips,
                                                   tmp_path):
    """Tracer-verified acceptance: a packed segment run records decode
    spans proportional to the covered range, not the video length."""
    from video_features_tpu.config import load_config
    from video_features_tpu.obs.spans import SpanRecorder
    from video_features_tpu.parallel.packing import VideoTask
    from video_features_tpu.registry import create_extractor
    from video_features_tpu.utils.tracing import Tracer

    clip = ingress_clips[0]                      # 16 frames @ 25 fps
    args = load_config('resnet', overrides={
        'device': 'cpu', 'model_name': 'resnet18', 'batch_size': 4,
        'allow_random_weights': True, 'on_extraction': 'save_numpy',
        'video_paths': [clip],
        'output_path': str(tmp_path / 'out'),
        'tmp_path': str(tmp_path / 'tmp')})
    ex = create_extractor(args)

    def run(segment, tag):
        rec = SpanRecorder()
        ex.tracer = Tracer(enabled=True, recorder=rec)
        ex.profile = False
        task = VideoTask(clip, segment=segment)
        task.out_root = str(tmp_path / tag)
        ex.extract_packed([task])
        return sum(1 for ev in rec.snapshot()
                   if ev.get('name') == 'decode+preprocess')

    full = run(None, 'full')
    seg = run((0.0, 0.2), 'seg')                 # 5 of 16 frames
    assert full >= 16
    assert 0 < seg <= 6                          # ∝ the range, + slack
    assert seg < full / 2


def test_quota_exhausted_sheds_without_admission_slot(gatewayed,
                                                      ingress_clips):
    """The satellite: a quota-shed request returns a structured error
    carrying tenant + request id, never occupies an admission slot, and
    increments vft_ingress_shed_total."""
    server, gateway, root = gatewayed
    depth_before = server.metrics()['queue']['depth']

    # burst=1: the first request drains the bucket (and may also pin the
    # 1-concurrency budget); the second MUST shed at the quota gate
    st1, d1 = _api(gateway, 'POST', '/v1/extract', {
        'feature_type': 'resnet', 'video_paths': [ingress_clips[1]],
        'overrides': {'output_path': str(root / 'q_out')}},
        key=LIMITED_KEY)
    assert st1 == 200, d1
    st2, d2 = _api(gateway, 'POST', '/v1/extract', {
        'feature_type': 'resnet', 'video_paths': [ingress_clips[1]]},
        key=LIMITED_KEY)
    assert st2 == 429, d2
    assert d2['error'] in ('rate_limited', 'concurrency')
    assert d2['tenant'] == 'capped' and 'request_id' in d2

    # shed never touched admission: depth unchanged by the rejection
    m = server.metrics()
    assert m['queue']['depth'] <= depth_before + 1  # only the accepted one
    assert m['ingress']['tenants']['capped']['shed'] >= 1

    st, text = _api(gateway, 'GET', '/metrics')
    assert st == 200
    shed_lines = [ln for ln in text.decode().splitlines()
                  if ln.startswith('vft_ingress_shed_total{')
                  and 'tenant="capped"' in ln]
    assert shed_lines and any(
        'class="interactive"' in ln and not ln.endswith(' 0')
        for ln in shed_lines), shed_lines

    _wait_done(gateway, d1['request_id'], key=LIMITED_KEY)


def test_batch_priority_shed_before_interactive(gatewayed):
    """queue_depth=8, batch_shed_fraction=0.5 → the batch class sees a
    capacity of 4: a 5-video batch submit is shed (structured, never
    occupying a slot) while the same submit as interactive admits."""
    server, gateway, root = gatewayed
    fakes = [f'/nonexistent/batchvid{i}.mp4' for i in range(5)]

    st, doc = _api(gateway, 'POST', '/v1/extract', {
        'feature_type': 'resnet', 'video_paths': fakes},
        key=BATCH_KEY)                          # tenant priority: batch
    assert st == 503 and doc['error'] == 'queue_full', doc
    assert doc['priority'] == 'batch' and doc['capacity'] == 4
    assert doc['tenant'] == 'bulkco'
    assert server.metrics()['queue']['depth'] == 0  # never admitted

    st, text = _api(gateway, 'GET', '/metrics')
    assert any('class="batch"' in ln and 'reason="queue_full"' in ln
               for ln in text.decode().splitlines()
               if ln.startswith('vft_ingress_shed_total{'))

    # the key's class is a CAP: a batch-provisioned tenant can't claim
    # interactive to dodge the shed
    st, doc = _api(gateway, 'POST', '/v1/extract', {
        'feature_type': 'resnet', 'video_paths': fakes,
        'priority': 'interactive'}, key=BATCH_KEY)
    assert st == 403 and doc['error'] == 'priority_forbidden', doc
    assert doc['tenant'] == 'bulkco'

    # the SAME videos from an INTERACTIVE tenant fit (full capacity 8);
    # they fail fast per-video (nonexistent files) through the normal
    # contract
    st, doc = _api(gateway, 'POST', '/v1/extract', {
        'feature_type': 'resnet', 'video_paths': fakes,
        'priority': 'interactive',
        'overrides': {'output_path': str(root / 'b_out')}})
    assert st == 200, doc
    status = _wait_done(gateway, doc['request_id'])
    assert status['state'] == 'failed'
    assert set(status['videos'].values()) == {'failed'}


def test_deadline_expired_over_ingress(gatewayed, ingress_clips):
    """The satellite's other half: a deadline that passes before decode
    starts expires the videos; the ingress status names tenant + request
    id and the expired count lands in the metrics families."""
    server, gateway, root = gatewayed
    # a ZERO deadline is expired by construction (monotonic() >= now) —
    # a warm worker can dequeue within any positive epsilon, so this is
    # the only race-free way to pin the expiry path
    st, doc = _api(gateway, 'POST', '/v1/extract', {
        'feature_type': 'resnet', 'video_paths': [ingress_clips[0]],
        'timeout_s': 0.0,
        'overrides': {'output_path': str(root / 'dl_out')}})
    assert st == 200, doc
    status = _wait_done(gateway, doc['request_id'])
    assert status['state'] == 'failed'
    assert set(status['videos'].values()) == {'expired'}
    assert status['tenant'] == 'acme'
    assert status['request_id'] == doc['request_id']
    assert server.metrics()['requests']['expired_videos'] >= 1


def _live_connect(gateway, sid, key=API_KEY, timeout=180):
    s = socket.create_connection(('127.0.0.1', gateway.port),
                                 timeout=timeout)
    s.sendall(f'POST /v1/live/{sid} HTTP/1.1\r\nHost: t\r\n'
              f'Authorization: Bearer {key}\r\n'
              f'Transfer-Encoding: chunked\r\n\r\n'.encode())
    return s


def _send_chunk(s, payload: bytes):
    s.sendall(b'%x\r\n%s\r\n' % (len(payload), payload))


def _frames_chunk(rng, n=3, h=48, w=64):
    buf = io.BytesIO()
    np.save(buf, rng.integers(0, 255, (n, h, w, 3), dtype=np.uint8))
    return buf.getvalue()


class _ChunkReader:
    """Minimal chunked-response reader over a raw socket."""

    def __init__(self, s):
        self.rf = s.makefile('rb')

    def read_headers(self):
        line = self.rf.readline()
        status = int(line.split()[1])
        while self.rf.readline() not in (b'\r\n', b''):
            pass
        return status

    def read_chunk(self):
        size = int(self.rf.readline().split(b';')[0], 16)
        if size == 0:
            self.rf.readline()
            return None
        data = self.rf.read(size)
        self.rf.readline()
        return data


def test_live_session_streams_windows_before_final(gatewayed):
    """Acceptance: a live session streams >= 2 per-window feature chunks
    BEFORE the final done-line; window count matches the frames sent."""
    server, gateway, _ = gatewayed
    rng = np.random.default_rng(7)
    s = _live_connect(gateway, 'live-a')
    try:
        _send_chunk(s, json.dumps(
            {'feature_type': 'resnet', 'fps': 5.0}).encode())
        reader = _ChunkReader(s)
        assert reader.read_headers() == 200
        hello = json.loads(reader.read_chunk())
        assert hello['ok'] and hello['session'] == 'live-a'
        rid = hello['request_id']

        _send_chunk(s, _frames_chunk(rng, n=3))
        _send_chunk(s, _frames_chunk(rng, n=3))
        rows = []
        while len(rows) < 6:                    # resnet: 1 frame = 1 window
            row = json.loads(reader.read_chunk())
            assert 'window' in row and not row.get('done'), row
            rows.append(row)
        # >= 2 per-window chunks arrived BEFORE end-of-input, each with
        # a feature vector + the fps-derived timestamp
        assert len(rows) >= 2
        assert len(rows[0]['feats']['resnet']) == 512
        assert rows[1]['timestamp_ms'] == pytest.approx(200.0)

        s.sendall(b'0\r\n\r\n')                 # end of input
        final = json.loads(reader.read_chunk())
        while not final.get('done'):
            final = json.loads(reader.read_chunk())
        assert final['state'] == 'done' and final['windows'] == 6
        assert final['request_id'] == rid
    finally:
        s.close()


def test_live_session_tail_windows_survive_immediate_end(gatewayed):
    """Regression (review): a client that sends its terminator right
    after the last frames — no idle lull, nothing read yet — must still
    receive EVERY window and a 'done' final state. (End-of-input used to
    tear the session down via the windower's finally, so tail windows
    still pooled in the packer hit a dead send_window and the task was
    marked failed.)"""
    server, gateway, _ = gatewayed
    rng = np.random.default_rng(13)
    s = _live_connect(gateway, 'tail-sid')
    try:
        _send_chunk(s, json.dumps(
            {'feature_type': 'resnet', 'fps': 5.0}).encode())
        # 3 frames (< batch_size 4: they pool) then the terminator
        # immediately — before reading a single response chunk
        _send_chunk(s, _frames_chunk(rng, n=3))
        s.sendall(b'0\r\n\r\n')
        reader = _ChunkReader(s)
        assert reader.read_headers() == 200
        assert json.loads(reader.read_chunk())['ok']
        rows = []
        final = None
        while True:
            row = json.loads(reader.read_chunk())
            if row.get('done'):
                final = row
                break
            rows.append(row)
        assert len(rows) == 3, rows
        assert final['state'] == 'done' and final['windows'] == 3
    finally:
        s.close()


def test_range_validation_rejects_nonfinite_and_bad_order(gatewayed):
    """Structured 400s for malformed ranges — including JSON's 1e999 →
    inf, which must never reach the decode thread as an OverflowError."""
    server, gateway, _ = gatewayed
    for bad in ([1.0], [2.0, 1.0], [-1.0, 2.0], [0.0, 1e999],
                ['a', 'b']):
        st, doc = _api(gateway, 'POST', '/v1/extract', {
            'feature_type': 'resnet',
            'video_paths': ['/nonexistent/r.mp4'], 'range': bad})
        assert st == 400, (bad, st, doc)
        assert doc['tenant'] == 'acme'


def test_duplicate_live_session_id_rejected(gatewayed):
    """Bugfix satellite: two in-flight sessions must not share an id —
    the second gets a structured 409 while the first keeps streaming."""
    server, gateway, _ = gatewayed
    rng = np.random.default_rng(8)
    s1 = _live_connect(gateway, 'dup-sid')
    try:
        _send_chunk(s1, json.dumps(
            {'feature_type': 'resnet', 'fps': 5.0}).encode())
        r1 = _ChunkReader(s1)
        assert r1.read_headers() == 200
        assert json.loads(r1.read_chunk())['ok']

        s2 = _live_connect(gateway, 'dup-sid')
        try:
            _send_chunk(s2, json.dumps(
                {'feature_type': 'resnet', 'fps': 5.0}).encode())
            r2 = _ChunkReader(s2)
            assert r2.read_headers() == 409
        finally:
            s2.close()

        # first session is unharmed: frames still round-trip
        _send_chunk(s1, _frames_chunk(rng, n=2))
        row = json.loads(r1.read_chunk())
        assert 'window' in row
        s1.sendall(b'0\r\n\r\n')
        final = json.loads(r1.read_chunk())
        while not final.get('done'):
            final = json.loads(r1.read_chunk())
        assert final['state'] == 'done'
    finally:
        s1.close()

    # the id is reusable once its session ended
    st, _doc = _api(gateway, 'GET', '/v1/metrics')
    assert st == 200
    assert server.metrics()['ingress']['live_sessions'] == 0


def test_live_session_rejected_for_nonlive_family(monkeypatch):
    """LIVE_FEATURES gates sessions up front with a clear error (all
    packed families currently opt in, so the gate is pinned by shrinking
    the set)."""
    from video_features_tpu.serve import server as server_mod

    class FakeSession:
        pseudo_path = 'x.live'

        def bind(self, req):
            pass

    monkeypatch.setattr(server_mod, 'LIVE_FEATURES',
                        frozenset({'resnet'}))
    server = server_mod.ExtractionServer(base_overrides={'device': 'cpu'})
    out = server.submit_live('r21d', FakeSession())
    assert out['ok'] is False and 'live-session support' in out['error']


def test_protocol_version_rejected_over_socket(gatewayed):
    """Satellite: unknown major version → structured error with the
    echoed request_id, not a silent parse failure; current version ok."""
    from video_features_tpu.serve import protocol
    server, _, _ = gatewayed

    def roundtrip(msg):
        s = socket.create_connection(('127.0.0.1', server.port),
                                     timeout=30)
        with s, s.makefile('rb') as rf:
            s.sendall(protocol.encode(msg))
            return protocol.decode(rf.readline())

    bad = roundtrip({'cmd': 'status', 'request_id': 'r000001',
                     'v': '99.1'})
    assert bad['ok'] is False
    assert 'unsupported protocol' in bad['error']
    assert bad['request_id'] == 'r000001'
    assert bad['v'] == protocol.VERSION

    good = roundtrip({'cmd': 'ping', 'v': protocol.VERSION})
    assert good['ok'] is True


@pytest.mark.slow
def test_segment_parity_through_decode_farm(ingress_clips, tmp_path):
    """Farm recipes included (tentpole piece 3): the worker PROCESSES
    replay the same frame-range filter, byte-identically to in-process
    segment decode."""
    from video_features_tpu.config import load_config
    from video_features_tpu.parallel.packing import VideoTask
    from video_features_tpu.registry import create_extractor

    clip = ingress_clips[0]
    args = load_config('resnet', overrides={
        'device': 'cpu', 'model_name': 'resnet18', 'batch_size': 4,
        'allow_random_weights': True, 'on_extraction': 'save_numpy',
        'video_paths': [clip],
        'output_path': str(tmp_path / 'out'),
        'tmp_path': str(tmp_path / 'tmp')})
    ex = create_extractor(args)
    seg = (0.2, 0.6)

    def run(tag, workers):
        task = VideoTask(clip, segment=seg)
        task.out_root = str(tmp_path / tag)
        ex.extract_packed([task], decode_workers=workers)
        stem = Path(clip).stem + '_seg200-600ms.mp4'
        return Path(make_path(task.out_root, stem,
                              'resnet', '.npy')).read_bytes()

    assert run('inproc', 1) == run('farm', 2)


@pytest.mark.slow
def test_live_session_through_decode_farm(tmp_path):
    """A farm-backed warm worker (decode_workers=2) runs live sessions
    on a parent-side feeder — frames never ship to a worker process —
    with the same streamed-windows contract."""
    from video_features_tpu.ingress.gateway import IngressGateway
    from video_features_tpu.serve.server import ExtractionServer

    base = _base_overrides(tmp_path)
    base['decode_workers'] = 2
    server = ExtractionServer(base_overrides=base, queue_depth=8,
                              pool_size=2).start()
    gateway = IngressGateway(server, auth=_make_auth()).start()
    rng = np.random.default_rng(11)
    s = _live_connect(gateway, 'farm-live')
    try:
        _send_chunk(s, json.dumps(
            {'feature_type': 'resnet', 'fps': 5.0}).encode())
        reader = _ChunkReader(s)
        assert reader.read_headers() == 200
        assert json.loads(reader.read_chunk())['ok']
        _send_chunk(s, _frames_chunk(rng, n=3))
        _send_chunk(s, _frames_chunk(rng, n=2))
        rows = []
        while len(rows) < 5:
            row = json.loads(reader.read_chunk())
            assert 'window' in row, row
            rows.append(row)
        assert len(rows[0]['feats']['resnet']) == 512
        s.sendall(b'0\r\n\r\n')
        final = json.loads(reader.read_chunk())
        while not final.get('done'):
            final = json.loads(reader.read_chunk())
        assert final['state'] == 'done' and final['windows'] == 5
    finally:
        s.close()
        server.drain(wait=True, grace_s=120)


def test_drain_reaps_half_open_live_session(tmp_path):
    """Bugfix satellite: a live client that stops mid-stream must not
    block drain — begin_drain ends its input, finish_drain force-closes
    the connection, and the warm pool is released."""
    from video_features_tpu.ingress.gateway import IngressGateway
    from video_features_tpu.serve.server import ExtractionServer

    server = ExtractionServer(base_overrides=_base_overrides(tmp_path),
                              queue_depth=8, pool_size=2).start()
    gateway = IngressGateway(server, auth=_make_auth()).start()
    rng = np.random.default_rng(9)
    s = _live_connect(gateway, 'half-open')
    _send_chunk(s, json.dumps(
        {'feature_type': 'resnet', 'fps': 5.0}).encode())
    reader = _ChunkReader(s)
    assert reader.read_headers() == 200
    assert json.loads(reader.read_chunk())['ok']
    _send_chunk(s, _frames_chunk(rng, n=2))
    # ... and the client goes silent: no end chunk, connection held open

    t0 = time.monotonic()
    server.drain(wait=True, grace_s=60)
    assert server.drained
    # drain completed promptly — the half-open session did not pin a
    # worker for the LIVE_IDLE_TIMEOUT (minutes)
    assert time.monotonic() - t0 < 45
    # the reaped handler thread's cleanup runs just after the force-
    # close; give it a beat before asserting the connection table empty
    deadline = time.monotonic() + 10
    while gateway.http.open_connections and time.monotonic() < deadline:
        time.sleep(0.05)
    assert gateway.http.open_connections == 0
    assert server.metrics()['ingress']['live_sessions'] == 0
    s.close()
