"""Fused multi-family worklists (``features=[...]``): decode once,
extract many.

The contract under test is BYTE-IDENTITY plus AMORTIZATION: a fused run
over N families produces exactly the files N sequential runs produce
(same names, same bytes, same cache keys), while decoding and
content-hashing each video exactly ONCE — the `decode_pass` instant and
`cache.key.hash_file_stats()` are the designed observables
(docs/decode_farm.md § multi-recipe).

Budget discipline (tier-1): ONE extractor per family for the whole
module (the transplant+compile dominates; the contracts are about the
LOOPS), tiny clips, and the farm/serve e2e variants are ``slow``.
"""
import os
import shutil
from pathlib import Path

import numpy as np
import pytest

from video_features_tpu.config import (
    load_config, load_fused_configs, resolve_fused_features,
    split_fused_overrides,
)
from video_features_tpu.registry import create_extractor
from video_features_tpu.utils.output import make_path

from tools.make_sample_video import write_noise_clip as _write_clip  # noqa: E402

FAMS = ('resnet', 'clip')
KEYS = {'resnet': ('resnet', 'fps', 'timestamps_ms'),
        'clip': ('clip', 'fps', 'timestamps_ms')}


# -- config layer (no jax device work) ---------------------------------------


def test_resolve_fused_features_normalizes_and_validates():
    assert resolve_fused_features(['resnet', 'clip']) == ['resnet', 'clip']
    # comma string (the non-YAML CLI spelling) and dedup, user order kept
    assert resolve_fused_features('clip, resnet,clip') == ['clip', 'resnet']
    # single family is legal — routes to the ordinary path
    assert resolve_fused_features('i3d') == ['i3d']
    with pytest.raises(ValueError, match='unknown family'):
        resolve_fused_features(['resnet', 'nosuch'])
    with pytest.raises(ValueError, match='at least one'):
        resolve_fused_features([])
    with pytest.raises(ValueError, match='features must be'):
        resolve_fused_features(42)


def test_split_fused_overrides_scopes_and_drops_routing_keys():
    shared, scoped = split_fused_overrides(
        {'features': ['resnet', 'clip'], 'feature_type': 'resnet',
         'batch_size': 4, 'clip.model_name': 'ViT-B/32',
         'resnet.batch_size': 8, 'some.dotted.path': 1},
        ['resnet', 'clip'])
    # routing keys never reach a merged config: 'features' leaking in
    # would fragment the fail-closed cache fingerprint vs sequential
    assert 'features' not in shared and 'feature_type' not in shared
    assert shared['batch_size'] == 4
    # a dotted key whose head is not a requested family stays shared
    assert shared['some.dotted.path'] == 1
    assert scoped['clip'] == {'model_name': 'ViT-B/32'}
    assert scoped['resnet'] == {'batch_size': 8}


def test_fused_configs_equal_sequential_configs(tmp_path):
    """Cache-key identity at its root: each family's fused merged config
    must equal the sequential `load_config(family, ...)` one — equal
    configs make `config_fingerprint` (and with the shared video hash,
    every per-(family, video) cache key) identical."""
    from video_features_tpu.cache import config_fingerprint
    over = dict(device='cpu', batch_size=4, allow_random_weights=True,
                on_extraction='save_numpy', output_path=str(tmp_path),
                tmp_path=str(tmp_path / 'tmp'))
    fused = load_fused_configs(
        ['resnet', 'clip'],
        overrides=dict(over, features=['resnet', 'clip'],
                       **{'resnet.model_name': 'resnet18',
                          'clip.model_name': 'ViT-B/32'}),
        run_sanity_check=False)
    seq = {'resnet': load_config('resnet',
                                 overrides=dict(over, model_name='resnet18'),
                                 run_sanity_check=False),
           'clip': load_config('clip',
                               overrides=dict(over, model_name='ViT-B/32'),
                               run_sanity_check=False)}
    for fam in ('resnet', 'clip'):
        assert dict(fused[fam]) == dict(seq[fam]), fam
        assert config_fingerprint(fused[fam]) == config_fingerprint(seq[fam])


# -- packer: per-family pooling ----------------------------------------------


def test_packed_batches_pool_per_family_at_own_cap():
    """Fused pools key (family, shape, dtype) and fill at THAT family's
    packed batch size — resnet/clip share 224x224x3 uint8 geometry, and
    a shared pool would feed one family's compiled program the other's
    batch capacity (a new program identity, an AOT-store miss)."""
    from video_features_tpu.parallel.packing import packed_batches
    from video_features_tpu.utils.tracing import NULL_TRACER

    win = np.zeros((4, 4, 3), dtype=np.uint8)

    def windows():
        for i in range(6):            # interleaved families, same shape
            yield f't{i}', win, ('a', i)
            yield f't{i}', win, ('b', i)

    out = list(packed_batches(windows(), 8, tracer=NULL_TRACER,
                              family_of=lambda m: m[0],
                              family_batch={'a': 2, 'b': 4}))
    got = [(m[0][1][0], len(m), v, s.shape[0]) for s, m, v in out if m]
    # family a flushes every 2 windows, family b every 4 — each padded
    # to its OWN capacity
    assert got == [('a', 2, 2, 2), ('a', 2, 2, 2), ('b', 4, 4, 4),
                   ('a', 2, 2, 2), ('b', 2, 2, 4)]
    for stacked, metas, valid in out:
        fams = {m[0] for _, m in metas}
        assert len(fams) == 1          # never mixed across families


def test_run_packed_fused_rejects_mismatched_signatures():
    class Fake:
        def __init__(self, sig):
            self._sig = sig

        def fused_decode_signature(self):
            return self._sig

    from video_features_tpu.parallel.packing import run_packed_fused
    with pytest.raises(ValueError, match='cannot share one decode pass'):
        run_packed_fused({'a': Fake(('framewise', None, None, 'auto')),
                          'b': Fake(('framewise', 5, None, 'auto'))}, [])
    with pytest.raises(ValueError, match='cannot share one decode pass'):
        run_packed_fused({'a': Fake(None), 'b': Fake(None)}, [])


# -- shared extractors (ONE per family for the whole module) -----------------


@pytest.fixture(scope='module')
def fused_clips(tmp_path_factory):
    d = tmp_path_factory.mktemp('fusedvids')
    return [str(_write_clip(d / f'fv{i}.mp4', n, seed=40 + i))
            for i, n in enumerate((7, 4))]


@pytest.fixture(scope='module')
def fused_exs(fused_clips, tmp_path_factory):
    base = tmp_path_factory.mktemp('fusedexs')
    models = {'resnet': 'resnet18', 'clip': 'ViT-B/32'}
    exs = {}
    for fam in FAMS:
        exs[fam] = create_extractor(load_config(fam, overrides=dict(
            video_paths=fused_clips, device='cpu', model_name=models[fam],
            batch_size=4, allow_random_weights=True,
            on_extraction='save_numpy', profile=True,
            output_path=str(base / 'out' / fam),
            tmp_path=str(base / 'tmp' / fam))))
    sigs = {f: e.fused_decode_signature() for f, e in exs.items()}
    assert len(set(sigs.values())) == 1 and None not in sigs.values(), sigs
    return exs


def _fused_tasks(exs, paths, root):
    from video_features_tpu.parallel.packing import FusedTask
    tasks = []
    for p in paths:
        c = FusedTask(p, list(exs))
        for fam, sub in c.subtasks.items():
            sub.out_root = str(Path(root) / fam)
        tasks.append(c)
    return tasks


def _run_fused(exs, tasks, **kw):
    """Run the fused driver with a fresh recorder on the lead tracer;
    returns the recorded events (the tracer itself stays module-shared)."""
    from video_features_tpu.obs.spans import SpanRecorder
    from video_features_tpu.parallel.packing import run_packed_fused
    lead = exs[next(iter(exs))]
    rec = SpanRecorder(capacity=4096)
    lead.tracer.recorder = rec
    try:
        run_packed_fused(exs, tasks, **kw)
    finally:
        lead.tracer.recorder = None
    return rec.snapshot()


def _outputs(root, paths, keys):
    return {(Path(p).name, k): np.load(make_path(str(root), p, k, '.npy'))
            for p in paths for k in keys}


@pytest.fixture(scope='module')
def fused_run(fused_exs, fused_clips, tmp_path_factory):
    """ONE fused pass + ONE sequential pass per family over the module
    extractors; several tests assert different contracts over it."""
    from video_features_tpu.parallel.packing import VideoTask
    root = tmp_path_factory.mktemp('fusedrun')
    events = _run_fused(fused_exs,
                        _fused_tasks(fused_exs, fused_clips, root / 'fused'))
    for fam, ex in fused_exs.items():
        ex.extract_packed([VideoTask(p, out_root=str(root / 'seq' / fam))
                           for p in fused_clips])
    return {'root': root, 'events': events}


def test_fused_outputs_byte_identical_to_sequential(fused_run, fused_exs,
                                                    fused_clips):
    root = fused_run['root']
    for fam in fused_exs:
        a = _outputs(root / 'seq' / fam, fused_clips, KEYS[fam])
        b = _outputs(root / 'fused' / fam, fused_clips, KEYS[fam])
        assert set(os.listdir(root / 'seq' / fam)) == \
            set(os.listdir(root / 'fused' / fam)), fam
        for key in a:
            np.testing.assert_array_equal(a[key], b[key],
                                          err_msg=f'{fam}:{key}')


def test_fused_run_decodes_each_video_exactly_once(fused_run, fused_exs,
                                                   fused_clips):
    """The amortization guard's decode half: exactly one `decode_pass`
    instant per video, each fanning out to EVERY family — N families'
    worth of outputs from one decode span set."""
    passes = [e for e in fused_run['events']
              if e['ph'] == 'i' and e['name'] == 'decode_pass']
    assert len(passes) == len(fused_clips)
    assert sorted(e['args']['video'] for e in passes) == sorted(fused_clips)
    for e in passes:
        assert e['args']['families'] == list(fused_exs)
    starts = [e for e in fused_run['events']
              if e['ph'] == 'i' and e['name'] == 'video_start']
    assert len(starts) == len(fused_clips)


def test_fused_run_hashes_each_video_exactly_once(fused_exs, fused_clips,
                                                  tmp_path):
    """The amortization guard's sha256 half: with the content cache on,
    a fused run streams each video's bytes through sha256 ONCE — every
    other family's cache key rides the stat-keyed memo. Fresh file
    copies make the memo provably cold."""
    from video_features_tpu.cache.key import (
        hash_file_stats, reset_hash_file_stats,
    )
    from video_features_tpu.cache.store import FeatureCache
    clips = [str(shutil.copy(p, tmp_path / Path(p).name))
             for p in fused_clips]
    cache = FeatureCache(str(tmp_path / 'cache'))
    for ex in fused_exs.values():
        assert ex.run_fingerprint is not None
        ex.cache = cache
    try:
        reset_hash_file_stats()
        events = _run_fused(fused_exs,
                            _fused_tasks(fused_exs, clips, tmp_path / 'out'))
        stats = hash_file_stats()
    finally:
        for ex in fused_exs.values():
            ex.cache = None
    assert stats['passes'] == len(clips), stats
    # admission keys for the second family + publish-time keys all memo
    assert stats['memo_hits'] >= len(clips), stats
    assert sum(1 for e in events
               if e['ph'] == 'i' and e['name'] == 'decode_pass') == len(clips)
    # and the cache now holds every (family, video) object
    assert cache.stats()['entries'] == len(fused_exs) * len(clips)


def test_fused_family_fault_isolated_to_its_subtask(fused_exs, fused_clips,
                                                    tmp_path):
    """One family's device-step fault must not poison its siblings: the
    shared decode keeps feeding the healthy family, whose outputs stay
    byte-identical to a clean run's."""
    boom_fam = 'clip'

    def boom(_dev):
        raise RuntimeError('injected device fault')

    orig = fused_exs[boom_fam].packed_step
    fused_exs[boom_fam].packed_step = boom
    try:
        _run_fused(fused_exs,
                   _fused_tasks(fused_exs, fused_clips, tmp_path / 'f'))
    finally:
        fused_exs[boom_fam].packed_step = orig
    ok_fam = 'resnet'
    got = _outputs(tmp_path / 'f' / ok_fam, fused_clips, KEYS[ok_fam])
    ref = _run_fused_single_reference(fused_exs, ok_fam, fused_clips,
                                      tmp_path / 'ref')
    for key in ref:
        np.testing.assert_array_equal(got[key], ref[key], err_msg=str(key))
    # the faulted family wrote nothing
    for p in fused_clips:
        assert not Path(make_path(str(tmp_path / 'f' / boom_fam), p,
                                  boom_fam, '.npy')).exists()


def _run_fused_single_reference(exs, fam, clips, root):
    from video_features_tpu.parallel.packing import VideoTask
    exs[fam].extract_packed([VideoTask(p, out_root=str(root))
                             for p in clips])
    return _outputs(root, clips, KEYS[fam])


def test_fused_decode_fault_fails_all_families_for_that_video_only(
        fused_exs, fused_clips, tmp_path):
    """A decode fault is the carrier's: the unopenable video fails for
    EVERY family, while the healthy videos' outputs are untouched."""
    bad = str(tmp_path / 'gone.mp4')          # never created
    worklist = fused_clips[:1] + [bad] + fused_clips[1:]
    _run_fused(fused_exs, _fused_tasks(fused_exs, worklist, tmp_path / 'd'))
    for fam in fused_exs:
        for p in fused_clips:
            assert Path(make_path(str(tmp_path / 'd' / fam), p, fam,
                                  '.npy')).exists(), (fam, p)
        assert not Path(make_path(str(tmp_path / 'd' / fam), bad, fam,
                                  '.npy')).exists(), fam


# -- CLI routing -------------------------------------------------------------


def test_cli_features_routes_fused(tmp_path, tmp_path_factory):
    """`features=[resnet]` exercises the fused CLI surface end to end
    (config fan-out, signature grouping, packed run) at single-family
    cost; the multi-family CLI pass is the slow lane's."""
    from video_features_tpu.cli import main
    d = tmp_path_factory.mktemp('clifused')
    clip_path = str(_write_clip(d / 'c.mp4', 4, seed=91))
    out = tmp_path / 'out'
    rc = main(['features=[resnet]', f'video_paths=[{clip_path}]',
               'device=cpu', 'model_name=resnet18', 'batch_size=4',
               'allow_random_weights=true', 'on_extraction=save_numpy',
               f'output_path={out}', f'tmp_path={tmp_path / "tmp"}'])
    assert rc == 0
    # sanity_check appends <family>/<model_name> to the output root
    final = out / 'resnet' / 'resnet18'
    for k in KEYS['resnet']:
        assert Path(make_path(str(final), clip_path, k, '.npy')).exists(), k


@pytest.mark.slow
def test_cli_features_multi_family_fused_e2e(tmp_path, tmp_path_factory):
    from video_features_tpu.cli import main
    d = tmp_path_factory.mktemp('clifused2')
    clip_path = str(_write_clip(d / 'c.mp4', 5, seed=92))
    out = tmp_path / 'out'
    rc = main(['features=[resnet,clip]', f'video_paths=[{clip_path}]',
               'device=cpu', 'batch_size=4', 'resnet.model_name=resnet18',
               'clip.model_name=ViT-B/32', 'allow_random_weights=true',
               'on_extraction=save_numpy', f'output_path={out}',
               f'tmp_path={tmp_path / "tmp"}'])
    assert rc == 0
    for fam, model in (('resnet', 'resnet18'), ('clip', 'ViT-B_32')):
        root = out / fam / model
        assert Path(make_path(str(root), clip_path, fam, '.npy')).exists(), \
            fam


# -- serve: fused submit ------------------------------------------------------


def test_serve_fused_submit_rejections(tmp_path):
    """The fan-out rejection surface costs no extraction: unknown
    families, non-packable families, and empty worklists reject the
    whole fused request before any child admits."""
    from video_features_tpu.serve.server import ExtractionServer
    srv = ExtractionServer(base_overrides={
        'device': 'cpu', 'model_name': 'resnet18', 'batch_size': 4,
        'allow_random_weights': True, 'on_extraction': 'save_numpy',
        'tmp_path': str(tmp_path / 'tmp'),
        'output_path': str(tmp_path / 'out')}, queue_depth=4).start()
    try:
        r = srv.submit(None, ['/x.mp4'], features=['resnet', 'nosuch'])
        assert not r['ok'] and 'nosuch' in r['error']
        r = srv.submit(None, ['/x.mp4'], features=['vggish'])
        assert not r['ok']
        r = srv.submit(None, [], features=['resnet'])
        assert not r['ok']
        r = srv.submit(None, ['/x.mp4'], features='')
        assert not r['ok']
    finally:
        srv.drain()


@pytest.mark.slow
def test_serve_fused_submit_e2e(tmp_path, tmp_path_factory):
    """Umbrella + per-family children over the loopback socket; a
    resubmit answers terminal-at-birth from the cache."""
    from video_features_tpu.serve.client import ServeClient
    from video_features_tpu.serve.server import ExtractionServer
    d = tmp_path_factory.mktemp('servefusedvids')
    clips = [str(_write_clip(d / f's{i}.mp4', n, seed=60 + i))
             for i, n in enumerate((6, 4))]
    srv = ExtractionServer(base_overrides={
        'device': 'cpu', 'model_name': 'resnet18', 'batch_size': 4,
        'allow_random_weights': True, 'on_extraction': 'save_numpy',
        'tmp_path': str(tmp_path / 'tmp'),
        'output_path': str(tmp_path / 'out'),
        'cache_enabled': True, 'cache_dir': str(tmp_path / 'cache')},
        queue_depth=32, pool_size=2).start()
    try:
        c = ServeClient(srv.port)
        over = {'clip.model_name': 'ViT-B/32'}
        rid = c.submit(None, clips, features=['resnet', 'clip'],
                       overrides=over)
        st = c.wait(rid, timeout_s=420)
        assert st['state'] == 'done'
        assert set(st['requests']) == {'resnet', 'clip'}
        assert set(st['videos']) == {'resnet', 'clip'}
        for fam, vids in st['videos'].items():
            assert set(vids) == set(clips)
            assert all(v in ('saved', 'cached') for v in vids.values()), \
                (fam, vids)
        # all-hit resubmit: terminal before the submit response returns
        rid2 = c.submit(None, clips, features=['resnet', 'clip'],
                        overrides=over)
        assert c.status(rid2)['state'] == 'done'
    finally:
        c.drain()


# -- decode farm --------------------------------------------------------------


@pytest.mark.slow
def test_fused_farm_matches_in_process(fused_exs, fused_clips, tmp_path):
    """decode_workers>1 ships the SAME FusedRecipe to the farm workers;
    the tagged window stream back over the ring must reproduce the
    in-process fused outputs byte for byte."""
    _run_fused(fused_exs,
               _fused_tasks(fused_exs, fused_clips, tmp_path / 'farm'),
               decode_workers=2)
    for fam in fused_exs:
        ref = _run_fused_single_reference(fused_exs, fam, fused_clips,
                                          tmp_path / 'ref' / fam)
        got = _outputs(tmp_path / 'farm' / fam, fused_clips, KEYS[fam])
        for key in ref:
            np.testing.assert_array_equal(got[key], ref[key],
                                          err_msg=f'{fam}:{key}')
