"""R(2+1)D: architecture shapes, transplant roundtrip, E2E extraction."""
from pathlib import Path

import numpy as np
import pytest

from video_features_tpu.config import load_config
from video_features_tpu.models import r21d as r21d_model
from video_features_tpu.registry import create_extractor
from video_features_tpu.transplant.torch2jax import transplant


def test_midplanes_formula():
    # torchvision VideoResNet Conv2Plus1D midplane budget
    assert r21d_model.midplanes(64, 64) == (64 * 64 * 27) // (64 * 9 + 3 * 64)


def test_forward_shapes():
    params = transplant(r21d_model.init_state_dict())
    x = np.random.RandomState(0).rand(2, 16, 112, 112, 3).astype(np.float32)
    feats = np.asarray(r21d_model.forward(params, x))
    assert feats.shape == (2, 512)
    logits = np.asarray(r21d_model.forward(params, x, features=False))
    assert logits.shape == (2, 400)


@pytest.mark.slow
def test_e2e_extraction(short_video, tmp_path):
    args = load_config('r21d', overrides={
        'video_paths': short_video,
        'device': 'cpu',
        'on_extraction': 'save_numpy',
        'output_path': str(tmp_path / 'out'),
        'tmp_path': str(tmp_path / 'tmp'),
    })
    ex = create_extractor(args)
    feats = ex.extract(short_video)
    f = feats['r21d']
    # 48 frames / stack 16 step 16 → 3 stacks
    assert f.shape == (3, 512)
    assert np.isfinite(f).all()

    # the full driver path writes the idempotent output file
    ex._extract(short_video)
    stem = Path(short_video).stem
    saved = np.load(tmp_path / 'out' / 'r21d' / 'r2plus1d_18_16_kinetics'
                    / f'{stem}_r21d.npy')
    np.testing.assert_allclose(saved, f, atol=1e-6)


@pytest.mark.slow
def test_forward_shapes_r34_variants():
    """The ig65m R(2+1)D-34 registry entries (reference extract_r21d.py:30-43):
    deeper blocks, 8- and 32-frame stacks, same 512-d features."""
    params = transplant(r21d_model.init_state_dict(arch='r2plus1d_34'))
    rng = np.random.RandomState(0)
    for stack in (8, 32):
        x = rng.rand(1, stack, 112, 112, 3).astype(np.float32)
        feats = np.asarray(r21d_model.forward(params, x, arch='r2plus1d_34'))
        assert feats.shape == (1, 512), stack
        assert np.isfinite(feats).all()


@pytest.mark.slow
def test_parity_vs_torch_mirror():
    """Numerics vs a state-dict-compatible torchvision VideoResNet mirror
    (R2Plus1dStem + Conv2Plus1D blocks) — the net behind reference
    extract_r21d.py:109-118 and BASELINE config 1. rel L2 < 1e-3 at
    float32."""
    import jax
    import torch

    from tests.torch_mirrors import TorchVideoResNet, randomize_bn_stats

    torch.manual_seed(0)
    mirror = TorchVideoResNet('r2plus1d_18').eval()
    randomize_bn_stats(mirror)
    params = transplant(mirror.state_dict())

    x = (np.random.RandomState(1).rand(2, 8, 56, 56, 3).astype(np.float32)
         * 2 - 1)
    with torch.no_grad():
        xt = torch.from_numpy(x).permute(0, 4, 1, 2, 3)  # NTHWC → NCTHW
        ref = mirror(xt).numpy()
        ref_logits = mirror(xt, features=False).numpy()
    with jax.default_matmul_precision('highest'):
        got = np.asarray(r21d_model.forward(params, x))
        got_logits = np.asarray(r21d_model.forward(params, x, features=False))

    for ours, theirs in ((got, ref), (got_logits, ref_logits)):
        rel = np.linalg.norm(ours - theirs) / np.linalg.norm(theirs)
        assert rel < 1e-3, f'rel L2 {rel}'
