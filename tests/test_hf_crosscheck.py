"""Cross-implementation checks against HuggingFace `transformers`.

The torch mirrors in tests/torch_mirrors.py are written in THIS repo, so a
shared misreading of an architecture could pass mirror parity. These tests
compare against `transformers`' independently written models (available in
the environment, config-instantiated offline with random weights): the HF
state dict is mechanically re-keyed into the timm layout our transplant
layer consumes, and both sides run the same input. Agreement here means
our numerics match code we had no hand in.

The reference consumes these architectures through pip-timm
(reference models/timm/extract_timm.py:48); HF's ViT is the same
published architecture (Dosovitskiy et al.) under a different module tree.
"""
from __future__ import annotations

import numpy as np
import pytest
import torch

from video_features_tpu.transplant.torch2jax import transplant

transformers = pytest.importorskip('transformers')


def _hf_vit_to_timm(hf_sd, depth):
    """HF ViTModel state dict → timm VisionTransformer naming (the layout
    models/vit.py mirrors). The only structural difference is HF's split
    q/k/v projections vs timm's packed qkv."""
    sd = {
        'cls_token': hf_sd['embeddings.cls_token'],
        'pos_embed': hf_sd['embeddings.position_embeddings'],
        'patch_embed.proj.weight':
            hf_sd['embeddings.patch_embeddings.projection.weight'],
        'patch_embed.proj.bias':
            hf_sd['embeddings.patch_embeddings.projection.bias'],
        'norm.weight': hf_sd['layernorm.weight'],
        'norm.bias': hf_sd['layernorm.bias'],
    }
    for i in range(depth):
        h, t = f'encoder.layer.{i}.', f'blocks.{i}.'
        for ours, theirs in [('norm1', 'layernorm_before'),
                             ('norm2', 'layernorm_after'),
                             ('attn.proj', 'attention.output.dense'),
                             ('mlp.fc1', 'intermediate.dense'),
                             ('mlp.fc2', 'output.dense')]:
            sd[t + ours + '.weight'] = hf_sd[h + theirs + '.weight']
            sd[t + ours + '.bias'] = hf_sd[h + theirs + '.bias']
        sd[t + 'attn.qkv.weight'] = torch.cat(
            [hf_sd[h + f'attention.attention.{p}.weight']
             for p in ('query', 'key', 'value')], dim=0)
        sd[t + 'attn.qkv.bias'] = torch.cat(
            [hf_sd[h + f'attention.attention.{p}.bias']
             for p in ('query', 'key', 'value')], dim=0)
    return sd


@pytest.mark.slow
def test_vit_parity_vs_hf_transformers():
    """vit_tiny geometry vs transformers.ViTModel: CLS-token feature after
    the final LN, rel L2 < 1e-3 at float32."""
    import jax

    from video_features_tpu.models import vit as vit_model

    cfg = vit_model.ARCHS['vit_tiny_patch16_224']
    hf_cfg = transformers.ViTConfig(
        hidden_size=cfg['width'], num_hidden_layers=cfg['layers'],
        num_attention_heads=cfg['heads'],
        intermediate_size=cfg['width'] * 4, image_size=224,
        patch_size=cfg['patch'], hidden_act='gelu',
        layer_norm_eps=1e-6,           # timm's eps (HF default is 1e-12)
        attention_probs_dropout_prob=0.0, hidden_dropout_prob=0.0)
    torch.manual_seed(0)
    hf = transformers.ViTModel(hf_cfg, add_pooling_layer=False).eval()

    params = transplant(_hf_vit_to_timm(hf.state_dict(), cfg['layers']))
    x = np.random.RandomState(1).rand(2, 224, 224, 3).astype(np.float32)
    x = x * 2 - 1
    with torch.no_grad():
        out = hf(torch.from_numpy(x).permute(0, 3, 1, 2))
        ref = out.last_hidden_state[:, 0].numpy()   # CLS after final LN
    with jax.default_matmul_precision('highest'):
        got = np.asarray(vit_model.forward(
            params, x, arch='vit_tiny_patch16_224', features=True))

    assert got.shape == ref.shape == (2, cfg['width'])
    rel = np.linalg.norm(got - ref) / np.linalg.norm(ref)
    assert rel < 1e-3, f'rel L2 vs transformers ViT: {rel}'


def _hf_convnext_to_timm(hf_sd, depths):
    """HF ConvNextModel state dict → timm ConvNeXt naming (the layout
    models/convnext.py mirrors)."""
    sd = {
        'stem.0.weight': hf_sd['embeddings.patch_embeddings.weight'],
        'stem.0.bias': hf_sd['embeddings.patch_embeddings.bias'],
        'stem.1.weight': hf_sd['embeddings.layernorm.weight'],
        'stem.1.bias': hf_sd['embeddings.layernorm.bias'],
        'head.norm.weight': hf_sd['layernorm.weight'],
        'head.norm.bias': hf_sd['layernorm.bias'],
    }
    for s, depth in enumerate(depths):
        h, t = f'encoder.stages.{s}.', f'stages.{s}.'
        if s > 0:
            for idx in ('0', '1'):
                for p in ('weight', 'bias'):
                    sd[f'{t}downsample.{idx}.{p}'] = hf_sd[
                        f'{h}downsampling_layer.{idx}.{p}']
        for j in range(depth):
            hb, tb = f'{h}layers.{j}.', f'{t}blocks.{j}.'
            sd[tb + 'gamma'] = hf_sd[hb + 'layer_scale_parameter']
            for ours, theirs in [('conv_dw', 'dwconv'),
                                 ('norm', 'layernorm'),
                                 ('mlp.fc1', 'pwconv1'),
                                 ('mlp.fc2', 'pwconv2')]:
                sd[tb + ours + '.weight'] = hf_sd[hb + theirs + '.weight']
                sd[tb + ours + '.bias'] = hf_sd[hb + theirs + '.bias']
    return sd


def test_convnext_parity_vs_hf_transformers():
    """convnext_tiny vs transformers.ConvNextModel: pooled feature after
    the head LayerNorm (HF pooler_output), rel L2 < 1e-3 at float32."""
    import jax

    from video_features_tpu.models import convnext as convnext_model

    cfg = convnext_model.ARCHS['convnext_tiny']
    hf_cfg = transformers.ConvNextConfig(
        depths=list(cfg['depths']), hidden_sizes=list(cfg['dims']),
        layer_norm_eps=1e-6, hidden_act='gelu')
    torch.manual_seed(0)
    hf = transformers.ConvNextModel(hf_cfg).eval()

    params = transplant(_hf_convnext_to_timm(hf.state_dict(),
                                             cfg['depths']))
    x = np.random.RandomState(1).rand(2, 96, 96, 3).astype(np.float32)
    x = x * 2 - 1
    with torch.no_grad():
        out = hf(torch.from_numpy(x).permute(0, 3, 1, 2))
        ref = out.pooler_output.numpy()      # LN(global mean pool)
    with jax.default_matmul_precision('highest'):
        got = np.asarray(convnext_model.forward(
            params, x, arch='convnext_tiny', features=True))

    assert got.shape == ref.shape == (2, cfg['dims'][-1])
    rel = np.linalg.norm(got - ref) / np.linalg.norm(ref)
    assert rel < 1e-3, f'rel L2 vs transformers ConvNext: {rel}'


def _hf_swin_to_timm(hf_sd, depths):
    """HF SwinModel state dict → timm 0.9.12 Swin naming (the layout
    models/swin.py mirrors). Structural differences: HF splits q/k/v
    (timm packs qkv), and HF hangs each PatchMerging off the END of
    stage L where timm 0.9.12 puts it at the START of stage L+1 —
    identical math, shifted key prefix."""
    sd = {
        'patch_embed.proj.weight':
            hf_sd['embeddings.patch_embeddings.projection.weight'],
        'patch_embed.proj.bias':
            hf_sd['embeddings.patch_embeddings.projection.bias'],
        'patch_embed.norm.weight': hf_sd['embeddings.norm.weight'],
        'patch_embed.norm.bias': hf_sd['embeddings.norm.bias'],
        'norm.weight': hf_sd['layernorm.weight'],
        'norm.bias': hf_sd['layernorm.bias'],
    }
    for li, depth in enumerate(depths):
        if li > 0:   # HF stage li-1's tail merge == timm stage li's head
            for ours, theirs in [('norm', 'norm'),
                                 ('reduction', 'reduction')]:
                for p in ('weight', 'bias'):
                    key = f'encoder.layers.{li - 1}.downsample.{theirs}.{p}'
                    if key in hf_sd:   # reduction has no bias
                        sd[f'layers.{li}.downsample.{ours}.{p}'] = hf_sd[key]
        for b in range(depth):
            h = f'encoder.layers.{li}.blocks.{b}.'
            t = f'layers.{li}.blocks.{b}.'
            sd[t + 'attn.relative_position_bias_table'] = hf_sd[
                h + 'attention.self.relative_position_bias_table']
            sd[t + 'attn.qkv.weight'] = torch.cat(
                [hf_sd[h + f'attention.self.{p}.weight']
                 for p in ('query', 'key', 'value')], dim=0)
            sd[t + 'attn.qkv.bias'] = torch.cat(
                [hf_sd[h + f'attention.self.{p}.bias']
                 for p in ('query', 'key', 'value')], dim=0)
            for ours, theirs in [('norm1', 'layernorm_before'),
                                 ('norm2', 'layernorm_after'),
                                 ('attn.proj', 'attention.output.dense'),
                                 ('mlp.fc1', 'intermediate.dense'),
                                 ('mlp.fc2', 'output.dense')]:
                sd[t + ours + '.weight'] = hf_sd[h + theirs + '.weight']
                sd[t + ours + '.bias'] = hf_sd[h + theirs + '.bias']
    return sd


@pytest.mark.slow
def test_swin_parity_vs_hf_transformers():
    """swin_tiny vs transformers.SwinModel at full 224 geometry (stage
    maps 56/28/14/7: real shift masks in stages 0-2, window-collapse in
    stage 3): mean-pooled feature after the final LN (HF pooler_output),
    rel L2 < 1e-3 at float32."""
    import jax

    from video_features_tpu.models import swin as swin_model

    depths = [2, 2, 6, 2]
    hf_cfg = transformers.SwinConfig(
        image_size=224, patch_size=4, embed_dim=96, depths=depths,
        num_heads=[3, 6, 12, 24], window_size=7, hidden_act='gelu',
        use_absolute_embeddings=False, layer_norm_eps=1e-5,
        drop_path_rate=0.0, attention_probs_dropout_prob=0.0,
        hidden_dropout_prob=0.0)
    torch.manual_seed(0)
    hf = transformers.SwinModel(hf_cfg, add_pooling_layer=True).eval()

    params = transplant(_hf_swin_to_timm(hf.state_dict(), depths))
    x = np.random.RandomState(1).rand(2, 224, 224, 3).astype(np.float32)
    x = x * 2 - 1
    with torch.no_grad():
        out = hf(torch.from_numpy(x).permute(0, 3, 1, 2))
        ref = out.pooler_output.numpy()      # mean over tokens after LN
    with jax.default_matmul_precision('highest'):
        got = np.asarray(swin_model.forward(
            params, x, arch='swin_tiny_patch4_window7_224'))

    assert got.shape == ref.shape == (2, 768)
    rel = np.linalg.norm(got - ref) / np.linalg.norm(ref)
    assert rel < 1e-3, f'rel L2 vs transformers Swin: {rel}'


def _hf_regnet_to_timm(hf_sd, depths):
    """HF RegNetModel ('y' layer type) state dict → timm 0.9.12 RegNet
    naming (the layout models/regnet.py mirrors). HF nests each block's
    conv stack in a Sequential (layer.0/1/3 = conv1/conv2/conv3, layer.2
    = SE with attention.0/attention.2 as reduce/expand) and calls the
    projection 'shortcut'."""
    sd = {}

    def cna(t, h):
        sd[f'{t}.conv.weight'] = hf_sd[f'{h}.convolution.weight']
        for p in ('weight', 'bias', 'running_mean', 'running_var'):
            sd[f'{t}.bn.{p}'] = hf_sd[f'{h}.normalization.{p}']

    cna('stem', 'embedder.embedder')
    for si, depth in enumerate(depths):
        for j in range(depth):
            h = f'encoder.stages.{si}.layers.{j}'
            t = f's{si + 1}.b{j + 1}'
            cna(f'{t}.conv1', f'{h}.layer.0')
            cna(f'{t}.conv2', f'{h}.layer.1')
            cna(f'{t}.conv3', f'{h}.layer.3')
            for ours, theirs in [('fc1', 'attention.0'),
                                 ('fc2', 'attention.2')]:
                for p in ('weight', 'bias'):
                    sd[f'{t}.se.{ours}.{p}'] = hf_sd[
                        f'{h}.layer.2.{theirs}.{p}']
            if f'{h}.shortcut.convolution.weight' in hf_sd:
                cna(f'{t}.downsample', f'{h}.shortcut')
    return sd


def test_regnet_parity_vs_hf_transformers():
    """regnety_008 vs transformers.RegNetModel: pooled feature (HF
    pooler_output), rel L2 < 1e-3 at float32. BN running stats and affine
    params are randomized so the transplant of those tensors is actually
    exercised (fresh BN is mean=0/var=1/γ=1/β=0, which would hide
    weight↔bias swaps)."""
    import jax

    from video_features_tpu.models import regnet as regnet_model

    depths, widths, group_w = regnet_model.ARCHS['regnety_008']
    hf_cfg = transformers.RegNetConfig(
        embedding_size=32, hidden_sizes=list(widths), depths=list(depths),
        groups_width=group_w, layer_type='y', hidden_act='relu')
    torch.manual_seed(0)
    hf = transformers.RegNetModel(hf_cfg).eval()
    gen = torch.Generator().manual_seed(3)
    for m in hf.modules():
        if isinstance(m, torch.nn.BatchNorm2d):
            m.running_mean = torch.randn(m.num_features, generator=gen) * 0.1
            m.running_var = torch.rand(m.num_features, generator=gen) + 0.5
            with torch.no_grad():
                m.weight.copy_(torch.rand(m.num_features, generator=gen)
                               * 0.2 + 0.9)
                m.bias.copy_(torch.randn(m.num_features, generator=gen)
                             * 0.02)

    params = transplant(_hf_regnet_to_timm(hf.state_dict(), depths))
    x = np.random.RandomState(1).rand(2, 128, 128, 3).astype(np.float32)
    x = x * 2 - 1
    with torch.no_grad():
        out = hf(torch.from_numpy(x).permute(0, 3, 1, 2))
        ref = out.pooler_output.numpy().reshape(2, -1)
    with jax.default_matmul_precision('highest'):
        got = np.asarray(regnet_model.forward(
            params, x, arch='regnety_008'))

    assert got.shape == ref.shape == (2, widths[-1])
    rel = np.linalg.norm(got - ref) / np.linalg.norm(ref)
    assert rel < 1e-3, f'rel L2 vs transformers RegNet: {rel}'
