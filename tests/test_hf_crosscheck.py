"""Cross-implementation checks against HuggingFace `transformers`.

The torch mirrors in tests/torch_mirrors.py are written in THIS repo, so a
shared misreading of an architecture could pass mirror parity. These tests
compare against `transformers`' independently written models (available in
the environment, config-instantiated offline with random weights): the HF
state dict is re-keyed into the timm layout by the PRODUCTION converters
(`transplant/hf.py`, the `tools/convert_checkpoint.py --hf-family` path),
and both sides run the same input. Agreement here means our numerics match
code we had no hand in — and validates the converter end-to-end.

The reference consumes these architectures through pip-timm
(reference models/timm/extract_timm.py:48); HF's ViT is the same
published architecture (Dosovitskiy et al.) under a different module tree.
"""
from __future__ import annotations

import numpy as np
import pytest
import torch

from video_features_tpu.transplant.hf import (
    convnext_to_timm, regnet_to_timm, swin_to_timm, vit_to_timm,
)
from video_features_tpu.transplant.torch2jax import transplant

transformers = pytest.importorskip('transformers')


@pytest.mark.slow
def test_vit_parity_vs_hf_transformers():
    """vit_tiny geometry vs transformers.ViTModel: CLS-token feature after
    the final LN, rel L2 < 1e-3 at float32."""
    import jax

    from video_features_tpu.models import vit as vit_model

    cfg = vit_model.ARCHS['vit_tiny_patch16_224']
    hf_cfg = transformers.ViTConfig(
        hidden_size=cfg['width'], num_hidden_layers=cfg['layers'],
        num_attention_heads=cfg['heads'],
        intermediate_size=cfg['width'] * 4, image_size=224,
        patch_size=cfg['patch'], hidden_act='gelu',
        layer_norm_eps=1e-6,           # timm's eps (HF default is 1e-12)
        attention_probs_dropout_prob=0.0, hidden_dropout_prob=0.0)
    torch.manual_seed(0)
    hf = transformers.ViTModel(hf_cfg, add_pooling_layer=False).eval()

    params = transplant(vit_to_timm(hf.state_dict(), 'vit_tiny_patch16_224'))
    x = np.random.RandomState(1).rand(2, 224, 224, 3).astype(np.float32)
    x = x * 2 - 1
    with torch.no_grad():
        out = hf(torch.from_numpy(x).permute(0, 3, 1, 2))
        ref = out.last_hidden_state[:, 0].numpy()   # CLS after final LN
    with jax.default_matmul_precision('highest'):
        got = np.asarray(vit_model.forward(
            params, x, arch='vit_tiny_patch16_224', features=True))

    assert got.shape == ref.shape == (2, cfg['width'])
    rel = np.linalg.norm(got - ref) / np.linalg.norm(ref)
    assert rel < 1e-3, f'rel L2 vs transformers ViT: {rel}'


def test_convnext_parity_vs_hf_transformers():
    """convnext_tiny vs transformers.ConvNextModel: pooled feature after
    the head LayerNorm (HF pooler_output), rel L2 < 1e-3 at float32."""
    import jax

    from video_features_tpu.models import convnext as convnext_model

    cfg = convnext_model.ARCHS['convnext_tiny']
    hf_cfg = transformers.ConvNextConfig(
        depths=list(cfg['depths']), hidden_sizes=list(cfg['dims']),
        layer_norm_eps=1e-6, hidden_act='gelu')
    torch.manual_seed(0)
    hf = transformers.ConvNextModel(hf_cfg).eval()

    params = transplant(convnext_to_timm(hf.state_dict(), 'convnext_tiny'))
    x = np.random.RandomState(1).rand(2, 96, 96, 3).astype(np.float32)
    x = x * 2 - 1
    with torch.no_grad():
        out = hf(torch.from_numpy(x).permute(0, 3, 1, 2))
        ref = out.pooler_output.numpy()      # LN(global mean pool)
    with jax.default_matmul_precision('highest'):
        got = np.asarray(convnext_model.forward(
            params, x, arch='convnext_tiny', features=True))

    assert got.shape == ref.shape == (2, cfg['dims'][-1])
    rel = np.linalg.norm(got - ref) / np.linalg.norm(ref)
    assert rel < 1e-3, f'rel L2 vs transformers ConvNext: {rel}'


@pytest.mark.slow
def test_swin_parity_vs_hf_transformers():
    """swin_tiny vs transformers.SwinModel at full 224 geometry (stage
    maps 56/28/14/7: real shift masks in stages 0-2, window-collapse in
    stage 3): mean-pooled feature after the final LN (HF pooler_output),
    rel L2 < 1e-3 at float32."""
    import jax

    from video_features_tpu.models import swin as swin_model

    depths = [2, 2, 6, 2]
    hf_cfg = transformers.SwinConfig(
        image_size=224, patch_size=4, embed_dim=96, depths=depths,
        num_heads=[3, 6, 12, 24], window_size=7, hidden_act='gelu',
        use_absolute_embeddings=False, layer_norm_eps=1e-5,
        drop_path_rate=0.0, attention_probs_dropout_prob=0.0,
        hidden_dropout_prob=0.0)
    torch.manual_seed(0)
    hf = transformers.SwinModel(hf_cfg, add_pooling_layer=True).eval()

    params = transplant(swin_to_timm(hf.state_dict(),
                                     'swin_tiny_patch4_window7_224'))
    x = np.random.RandomState(1).rand(2, 224, 224, 3).astype(np.float32)
    x = x * 2 - 1
    with torch.no_grad():
        out = hf(torch.from_numpy(x).permute(0, 3, 1, 2))
        ref = out.pooler_output.numpy()      # mean over tokens after LN
    with jax.default_matmul_precision('highest'):
        got = np.asarray(swin_model.forward(
            params, x, arch='swin_tiny_patch4_window7_224'))

    assert got.shape == ref.shape == (2, 768)
    rel = np.linalg.norm(got - ref) / np.linalg.norm(ref)
    assert rel < 1e-3, f'rel L2 vs transformers Swin: {rel}'


def test_regnet_parity_vs_hf_transformers():
    """regnety_008 vs transformers.RegNetModel: pooled feature (HF
    pooler_output), rel L2 < 1e-3 at float32. BN running stats and affine
    params are randomized so the transplant of those tensors is actually
    exercised (fresh BN is mean=0/var=1/γ=1/β=0, which would hide
    weight↔bias swaps)."""
    import jax

    from video_features_tpu.models import regnet as regnet_model

    depths, widths, group_w = regnet_model.ARCHS['regnety_008']
    hf_cfg = transformers.RegNetConfig(
        embedding_size=32, hidden_sizes=list(widths), depths=list(depths),
        groups_width=group_w, layer_type='y', hidden_act='relu')
    torch.manual_seed(0)
    hf = transformers.RegNetModel(hf_cfg).eval()
    gen = torch.Generator().manual_seed(3)
    for m in hf.modules():
        if isinstance(m, torch.nn.BatchNorm2d):
            m.running_mean = torch.randn(m.num_features, generator=gen) * 0.1
            m.running_var = torch.rand(m.num_features, generator=gen) + 0.5
            with torch.no_grad():
                m.weight.copy_(torch.rand(m.num_features, generator=gen)
                               * 0.2 + 0.9)
                m.bias.copy_(torch.randn(m.num_features, generator=gen)
                             * 0.02)

    params = transplant(regnet_to_timm(hf.state_dict(), 'regnety_008'))
    x = np.random.RandomState(1).rand(2, 128, 128, 3).astype(np.float32)
    x = x * 2 - 1
    with torch.no_grad():
        out = hf(torch.from_numpy(x).permute(0, 3, 1, 2))
        ref = out.pooler_output.numpy().reshape(2, -1)
    with jax.default_matmul_precision('highest'):
        got = np.asarray(regnet_model.forward(
            params, x, arch='regnety_008'))

    assert got.shape == ref.shape == (2, widths[-1])
    rel = np.linalg.norm(got - ref) / np.linalg.norm(ref)
    assert rel < 1e-3, f'rel L2 vs transformers RegNet: {rel}'


def test_convert_checkpoint_hf_family_cli(tmp_path):
    """tools/convert_checkpoint.py --hf-family: a (task-prefixed) HF ViT
    checkpoint converts to a torch-free .npz whose pytree loads into our
    forward — the no-pip-timm weights-provisioning path end-to-end."""
    import subprocess
    import sys

    import jax

    from tests.conftest import REPO_ROOT
    from video_features_tpu.models import vit as vit_model
    from video_features_tpu.transplant.torch2jax import load_torch_checkpoint

    cfg = vit_model.ARCHS['vit_tiny_patch16_224']
    hf_cfg = transformers.ViTConfig(
        hidden_size=cfg['width'], num_hidden_layers=cfg['layers'],
        num_attention_heads=cfg['heads'],
        intermediate_size=cfg['width'] * 4, image_size=224,
        patch_size=cfg['patch'], layer_norm_eps=1e-6)
    torch.manual_seed(0)
    hf = transformers.ViTModel(hf_cfg, add_pooling_layer=False).eval()
    # simulate a *ForImageClassification checkpoint: vit.-prefixed keys
    src = tmp_path / 'pytorch_model.bin'
    torch.save({f'vit.{k}': v for k, v in hf.state_dict().items()}, src)

    dst = tmp_path / 'vit_tiny.npz'
    proc = subprocess.run(
        [sys.executable, str(REPO_ROOT / 'tools' / 'convert_checkpoint.py'),
         str(src), str(dst),
         '--hf-family', 'vit', '--arch', 'vit_tiny_patch16_224'],
        cwd=str(REPO_ROOT), capture_output=True, text=True)
    assert proc.returncode == 0, proc.stderr

    params = load_torch_checkpoint(str(dst))
    x = np.random.RandomState(2).rand(1, 224, 224, 3).astype(np.float32)
    with jax.default_matmul_precision('highest'):
        got = np.asarray(vit_model.forward(
            params, x, arch='vit_tiny_patch16_224', features=True))
    with torch.no_grad():
        ref = hf(torch.from_numpy(x).permute(0, 3, 1, 2)
                 ).last_hidden_state[:, 0].numpy()
    rel = np.linalg.norm(got - ref) / np.linalg.norm(ref)
    assert rel < 1e-3, f'converted-checkpoint rel L2: {rel}'


@pytest.mark.slow
def test_deit_distilled_parity_vs_hf_transformers():
    """Distilled DeiT (vit_tiny geometry) vs transformers.DeiTModel: the
    dist_token dispatch against code we didn't write — feature = mean of
    the cls and distillation tokens after the final LN."""
    import jax

    from video_features_tpu.transplant.hf import deit_to_timm
    from video_features_tpu.models import vit as vit_model

    cfg = vit_model.ARCHS['vit_tiny_patch16_224']
    hf_cfg = transformers.DeiTConfig(
        hidden_size=cfg['width'], num_hidden_layers=cfg['layers'],
        num_attention_heads=cfg['heads'],
        intermediate_size=cfg['width'] * 4, image_size=224,
        patch_size=cfg['patch'], hidden_act='gelu', layer_norm_eps=1e-6)
    torch.manual_seed(0)
    hf = transformers.DeiTModel(hf_cfg, add_pooling_layer=False).eval()

    params = transplant(deit_to_timm(hf.state_dict(),
                                     'vit_tiny_patch16_224'))
    assert 'dist_token' in params
    x = np.random.RandomState(1).rand(2, 224, 224, 3).astype(np.float32)
    x = x * 2 - 1
    with torch.no_grad():
        out = hf(torch.from_numpy(x).permute(0, 3, 1, 2)).last_hidden_state
        ref = ((out[:, 0] + out[:, 1]) / 2).numpy()   # timm deit feature
    with jax.default_matmul_precision('highest'):
        got = np.asarray(vit_model.forward(
            params, x, arch='vit_tiny_patch16_224', features=True))

    assert got.shape == ref.shape == (2, cfg['width'])
    rel = np.linalg.norm(got - ref) / np.linalg.norm(ref)
    assert rel < 1e-3, f'rel L2 vs transformers DeiT: {rel}'


@pytest.mark.slow
def test_beit_parity_vs_hf_transformers():
    """beit_base vs transformers.BeitModel at full 224 geometry: per-block
    relative position bias (index taken from the HF buffers), q/v-only
    biases, lambda→gamma layer scale, mean-pooled patch tokens through the
    pooler LN — the structurally richest mapping, against code we didn't
    write."""
    import jax

    from video_features_tpu.models import beit as beit_model
    from video_features_tpu.transplant.hf import beit_to_timm

    hf_cfg = transformers.BeitConfig(
        hidden_size=768, num_hidden_layers=12, num_attention_heads=12,
        intermediate_size=3072, image_size=224, patch_size=16,
        use_relative_position_bias=True,
        use_absolute_position_embeddings=False, use_mean_pooling=True,
        layer_scale_init_value=0.1, layer_norm_eps=1e-6,
        hidden_act='gelu')
    torch.manual_seed(0)
    hf = transformers.BeitModel(hf_cfg, add_pooling_layer=True).eval()
    # HF zero-inits the bias tables; randomize so the lookup is exercised
    gen = torch.Generator().manual_seed(5)
    with torch.no_grad():
        for layer in hf.encoder.layer:
            layer.attention.attention.relative_position_bias \
                .relative_position_bias_table.normal_(0, 0.05, generator=gen)

    params = transplant(beit_to_timm(hf.state_dict(),
                                     'beit_base_patch16_224'))
    x = np.random.RandomState(1).rand(1, 224, 224, 3).astype(np.float32)
    x = x * 2 - 1
    with torch.no_grad():
        out = hf(torch.from_numpy(x).permute(0, 3, 1, 2))
        ref = out.pooler_output.numpy()
    with jax.default_matmul_precision('highest'):
        got = np.asarray(beit_model.forward(
            params, x, arch='beit_base_patch16_224'))

    assert got.shape == ref.shape == (1, 768)
    rel = np.linalg.norm(got - ref) / np.linalg.norm(ref)
    assert rel < 1e-3, f'rel L2 vs transformers Beit: {rel}'


@pytest.mark.slow
def test_clip_vitb32_full_geometry_vs_hf_transformers():
    """CLIP ViT-B/32 at FULL geometry vs transformers.CLIPModel — image
    tower, text tower, and logit_scale, against code we didn't write —
    replacing the reduced-geometry caveat on the in-repo CLIP parity row.
    The harness is shared with the PARITY.md row generator
    (tests/clip_crosscheck.py); the HF state dict goes through the
    PRODUCTION converter (transplant/hf.py:clip_to_openai, the
    --hf-family clip path)."""
    from tests.clip_crosscheck import run_clip_vitb32_crosscheck

    r = run_clip_vitb32_crosscheck()
    assert r['got_img'].shape == r['ref_img'].shape == (2, 512)
    assert r['got_txt'].shape == r['ref_txt'].shape == (2, 512)
    for part in ('img', 'txt', 'logits'):
        rel = (np.linalg.norm(r[f'got_{part}'] - r[f'ref_{part}'])
               / np.linalg.norm(r[f'ref_{part}']))
        assert rel < 1e-3, f'{part} rel L2 vs transformers: {rel}'


def test_regnetx_parity_vs_hf_transformers():
    """SE-free regnetx_008 vs transformers.RegNetModel layer_type='x':
    the converter's checkpoint-driven SE dispatch (layer.2 = conv3, no
    attention keys) against HF's own x-branch implementation."""
    import jax

    from video_features_tpu.models import regnet as regnet_model

    depths, widths, group_w = regnet_model.ARCHS['regnetx_008']
    hf_cfg = transformers.RegNetConfig(
        embedding_size=32, hidden_sizes=list(widths), depths=list(depths),
        groups_width=group_w, layer_type='x', hidden_act='relu')
    torch.manual_seed(0)
    hf = transformers.RegNetModel(hf_cfg).eval()

    params = transplant(regnet_to_timm(hf.state_dict(), 'regnetx_008'))
    x = np.random.RandomState(1).rand(1, 96, 96, 3).astype(np.float32)
    with torch.no_grad():
        ref = hf(torch.from_numpy(x).permute(0, 3, 1, 2)
                 ).pooler_output.numpy().reshape(1, -1)
    with jax.default_matmul_precision('highest'):
        got = np.asarray(regnet_model.forward(
            params, x, arch='regnetx_008'))
    rel = np.linalg.norm(got - ref) / np.linalg.norm(ref)
    assert rel < 1e-3, f'rel L2 vs transformers RegNetX: {rel}'
