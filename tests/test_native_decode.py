"""Native C++ decode service: frame parity vs cv2, props, VideoLoader
backend integration, and the prefetch pipelining wrapper."""
import numpy as np
import pytest

from video_features_tpu.io import native
from video_features_tpu.io.video import Cv2FrameDecoder, VideoLoader, prefetch

needs_native = pytest.mark.skipif(
    not native.available(), reason='libvfdecode.so unavailable')


@needs_native
def test_frame_parity_vs_cv2(sample_video_2):
    nat = list(native.NativeFrameDecoder(sample_video_2))
    cv = list(Cv2FrameDecoder(sample_video_2))
    assert len(nat) == len(cv) > 0
    for (i, a), (j, b) in zip(nat[:64], cv[:64]):
        assert i == j
        np.testing.assert_array_equal(a, b)


@needs_native
def test_props(sample_video_2):
    import cv2
    dec = native.NativeFrameDecoder(sample_video_2).open()
    cap = cv2.VideoCapture(sample_video_2)
    assert dec.fps == pytest.approx(cap.get(cv2.CAP_PROP_FPS), rel=1e-3)
    assert dec.width == int(cap.get(cv2.CAP_PROP_FRAME_WIDTH))
    assert dec.height == int(cap.get(cv2.CAP_PROP_FRAME_HEIGHT))
    assert dec.num_frames == int(cap.get(cv2.CAP_PROP_FRAME_COUNT))
    cap.release()
    dec.release()


@needs_native
def test_open_error():
    with pytest.raises(IOError):
        native.NativeFrameDecoder('/nonexistent/clip.mp4').open()


@needs_native
def test_videoloader_backend_equivalence(short_video):
    def batches(backend):
        loader = VideoLoader(short_video, batch_size=16, overlap=1,
                             backend=backend)
        return [(b, t, i) for b, t, i in loader]

    nat, cv = batches('native'), batches('cv2')
    assert len(nat) == len(cv)
    for (nb, nt, ni), (cb, ct, ci) in zip(nat, cv):
        np.testing.assert_array_equal(nb, cb)
        assert nt == ct and ni == ci


@needs_native
def test_videoloader_native_with_fps_resample(short_video):
    """Index-map fps retiming must work over the native decoder too."""
    loader = VideoLoader(short_video, batch_size=8, fps=10,
                         use_ffmpeg=False, backend='native')
    frames = [f for b, _, _ in loader for f in b]
    ref = VideoLoader(short_video, batch_size=8, fps=10,
                      use_ffmpeg=False, backend='cv2')
    ref_frames = [f for b, _, _ in ref for f in b]
    assert len(frames) == len(ref_frames) > 0
    np.testing.assert_array_equal(np.stack(frames), np.stack(ref_frames))


def test_prefetch_order_and_completeness():
    items = list(range(100))
    assert list(prefetch(iter(items), depth=3)) == items


def test_prefetch_propagates_exception():
    def gen():
        yield 1
        raise ValueError('decode failed')

    it = prefetch(gen(), depth=2)
    assert next(it) == 1
    with pytest.raises(ValueError, match='decode failed'):
        list(it)


def test_prefetch_early_close():
    """Abandoning the consumer must not deadlock the producer thread."""
    def gen():
        for i in range(10_000):
            yield i

    it = prefetch(gen(), depth=2)
    assert next(it) == 0
    it.close()
