"""Native C++ decode service: frame parity vs cv2, props, VideoLoader
backend integration, and the prefetch pipelining wrapper."""
import numpy as np
import pytest

from video_features_tpu.io import native
from video_features_tpu.io.video import Cv2FrameDecoder, VideoLoader, prefetch

needs_native = pytest.mark.skipif(
    not native.available(), reason='libvfdecode.so unavailable')


@needs_native
def test_frame_parity_vs_cv2(sample_video_2):
    nat = list(native.NativeFrameDecoder(sample_video_2))
    cv = list(Cv2FrameDecoder(sample_video_2))
    assert len(nat) == len(cv) > 0
    for (i, a), (j, b) in zip(nat[:64], cv[:64]):
        assert i == j
        np.testing.assert_array_equal(a, b)


@needs_native
def test_props(sample_video_2):
    import cv2
    dec = native.NativeFrameDecoder(sample_video_2).open()
    cap = cv2.VideoCapture(sample_video_2)
    assert dec.fps == pytest.approx(cap.get(cv2.CAP_PROP_FPS), rel=1e-3)
    assert dec.width == int(cap.get(cv2.CAP_PROP_FRAME_WIDTH))
    assert dec.height == int(cap.get(cv2.CAP_PROP_FRAME_HEIGHT))
    assert dec.num_frames == int(cap.get(cv2.CAP_PROP_FRAME_COUNT))
    cap.release()
    dec.release()


@needs_native
def test_open_error():
    with pytest.raises(IOError):
        native.NativeFrameDecoder('/nonexistent/clip.mp4').open()


@needs_native
def test_videoloader_backend_equivalence(short_video):
    def batches(backend):
        loader = VideoLoader(short_video, batch_size=16, overlap=1,
                             backend=backend)
        return [(b, t, i) for b, t, i in loader]

    nat, cv = batches('native'), batches('cv2')
    assert len(nat) == len(cv)
    for (nb, nt, ni), (cb, ct, ci) in zip(nat, cv):
        np.testing.assert_array_equal(nb, cb)
        assert nt == ct and ni == ci


@needs_native
def test_videoloader_native_with_fps_resample(short_video):
    """Index-map fps retiming must work over the native decoder too."""
    loader = VideoLoader(short_video, batch_size=8, fps=10,
                         use_ffmpeg=False, backend='native')
    frames = [f for b, _, _ in loader for f in b]
    ref = VideoLoader(short_video, batch_size=8, fps=10,
                      use_ffmpeg=False, backend='cv2')
    ref_frames = [f for b, _, _ in ref for f in b]
    assert len(frames) == len(ref_frames) > 0
    np.testing.assert_array_equal(np.stack(frames), np.stack(ref_frames))


def test_prefetch_order_and_completeness():
    items = list(range(100))
    assert list(prefetch(iter(items), depth=3)) == items


def test_prefetch_propagates_exception():
    def gen():
        yield 1
        raise ValueError('decode failed')

    it = prefetch(gen(), depth=2)
    assert next(it) == 1
    with pytest.raises(ValueError, match='decode failed'):
        list(it)


def test_prefetch_early_close():
    """Abandoning the consumer must not deadlock the producer thread."""
    def gen():
        for i in range(10_000):
            yield i

    it = prefetch(gen(), depth=2)
    assert next(it) == 0
    it.close()


def _patch_tkhd_rotation(src: str, dst: str) -> None:
    """Binary-patch the mp4 tkhd display matrix to a 90° cw rotation."""
    import struct

    data = bytearray(open(src, 'rb').read())
    i = data.find(b'tkhd')
    assert i > 0, 'no tkhd box in test clip'
    m = i + 4 + 1 + 3 + 20 + 16  # v0 tkhd: matrix is 44 bytes after fourcc
    data[m:m + 36] = struct.pack(
        '>9i', 0, 0x00010000, 0, -0x00010000, 0, 0, 0, 0, 0x40000000)
    open(dst, 'wb').write(data)


def test_rotation_metadata(short_video, tmp_path):
    """Display-matrix rotation is applied like cv2's auto-rotate.

    Phone portrait videos carry a rotate-90 display matrix; the native
    backend must yield the same upright frames and swapped dims as cv2, or
    backend='auto' silently changes orientation semantics.
    """
    rot = str(tmp_path / 'rot90.mp4')
    _patch_tkhd_rotation(short_video, rot)

    dec = native.NativeFrameDecoder(rot).open()
    assert dec.rotation == 90
    plain = native.NativeFrameDecoder(short_video).open()
    assert plain.rotation == 0
    assert (dec.width, dec.height) == (plain.height, plain.width)
    plain.release()

    nat = [f.copy() for _, f in zip(range(4), (fr for _, fr in dec))]
    cv = [f for _, f in zip(range(4), (fr for _, fr in Cv2FrameDecoder(rot)))]
    if cv[0].shape != nat[0].shape:
        pytest.skip('this cv2 build does not auto-rotate')
    np.testing.assert_array_equal(np.stack(nat), np.stack(cv))
