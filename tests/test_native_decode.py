"""Native C++ decode service: frame parity vs cv2, props, VideoLoader
backend integration, and the prefetch pipelining wrapper."""
import numpy as np
import pytest

from video_features_tpu.io import native
from video_features_tpu.io.video import Cv2FrameDecoder, VideoLoader, prefetch

needs_native = pytest.mark.skipif(
    not native.available(), reason='libvfdecode.so unavailable')


def _fitted_cv2_version() -> str:
    """The cv2 version the committed conversion tables were fitted
    against (stamped into the generated header by the fit tool)."""
    import re
    from pathlib import Path

    hdr = (Path(__file__).resolve().parents[1] / 'native'
           / 'yuv2rgb_cv2_tables.h')
    m = re.search(r'FITTED_CV2_VERSION: (\S+)', hdr.read_text())
    return m.group(1) if m else ''


def _cv2_matches_fit() -> bool:
    import cv2
    return cv2.__version__ == _fitted_cv2_version()


def assert_frames_close(a, b, smooth=False):
    """Native vs cv2 frames.

    When the running cv2 matches the build the conversion tables were
    fitted against (native/yuv2rgb_cv2_tables.h FITTED_CV2_VERSION):
    BIT-EXACT for 8-bit 4:2:0 limited-range content — any nonzero delta
    is a regression in that contract. On a DIFFERENT cv2 build (e.g. CI
    installing another opencv whose bundled swscale generation differs),
    exact equality is not the contract — the tables reproduce the fitted
    build — so assert the conversion-rounding band instead and rely on
    the matching-build environments for the exact pin; refit with
    tools/fit_cv2_yuv_tables.py to re-pin against a new cv2.

    The mean band catches systematic breakage (a wrong matrix is tens of
    levels on saturated colors). A hard per-pixel max is only meaningful
    on SMOOTH fixtures (``smooth=True``): on noisy/blocky content another
    swscale generation legitimately lands far from the fitted build at
    individual chroma edges (different chroma upsampling taps), and
    pinning ``max`` there flakes CI without proving anything about the
    conversion."""
    a = np.asarray(a)
    b = np.asarray(b)
    if _cv2_matches_fit():
        np.testing.assert_array_equal(a, b)
        return
    d = np.abs(a.astype(np.int32) - b.astype(np.int32))
    assert d.mean() <= 2.0, f'mean delta {d.mean()} (cv2 build differs ' \
        f'from fitted {_fitted_cv2_version()} — refit if this persists)'
    if smooth:
        assert d.max() <= 64, f'max delta {d.max()}'


@needs_native
def test_frame_parity_vs_cv2(sample_video_2):
    nat = list(native.NativeFrameDecoder(sample_video_2))
    cv = list(Cv2FrameDecoder(sample_video_2))
    assert len(nat) == len(cv) > 0
    for (i, a), (j, b) in zip(nat[:64], cv[:64]):
        assert i == j
        assert_frames_close(a, b)


@needs_native
def test_frame_bitexact_extreme_colors(tmp_path):
    """Bit-exactness holds at the YUV gamut boundary, where clipping and
    the rarely-exercised table entries live: beta-distributed RGB noise
    in 16px blocks survives 4:2:0 + DCT with extreme chroma intact."""
    import cv2
    path = str(tmp_path / 'extreme.mp4')
    rng = np.random.RandomState(11)
    w, h = 320, 240
    wr = cv2.VideoWriter(path, cv2.VideoWriter_fourcc(*'mp4v'), 25.0, (w, h))
    for _ in range(10):
        small = (255 * rng.beta(0.2, 0.2, (h // 16, w // 16, 3))).astype(np.uint8)
        wr.write(np.repeat(np.repeat(small, 16, 0), 16, 1))
    wr.release()
    nat = list(native.NativeFrameDecoder(path))
    cv = list(Cv2FrameDecoder(path))
    assert len(nat) == len(cv) == 10
    for (_, a), (_, b) in zip(nat, cv):
        assert_frames_close(a, b)


@needs_native
def test_props(sample_video_2):
    import cv2
    dec = native.NativeFrameDecoder(sample_video_2).open()
    cap = cv2.VideoCapture(sample_video_2)
    assert dec.fps == pytest.approx(cap.get(cv2.CAP_PROP_FPS), rel=1e-3)
    assert dec.width == int(cap.get(cv2.CAP_PROP_FRAME_WIDTH))
    assert dec.height == int(cap.get(cv2.CAP_PROP_FRAME_HEIGHT))
    assert dec.num_frames == int(cap.get(cv2.CAP_PROP_FRAME_COUNT))
    cap.release()
    dec.release()


@needs_native
def test_open_error():
    with pytest.raises(IOError):
        native.NativeFrameDecoder('/nonexistent/clip.mp4').open()


@needs_native
def test_videoloader_backend_equivalence(short_video):
    def batches(backend):
        loader = VideoLoader(short_video, batch_size=16, overlap=1,
                             backend=backend)
        return [(b, t, i) for b, t, i in loader]

    nat, cv = batches('native'), batches('cv2')
    assert len(nat) == len(cv)
    for (nb, nt, ni), (cb, ct, ci) in zip(nat, cv):
        assert nb.shape == cb.shape
        assert_frames_close(nb, cb)
        assert nt == ct and ni == ci


@needs_native
def test_videoloader_native_with_fps_resample(short_video):
    """Index-map fps retiming must work over the native decoder too."""
    loader = VideoLoader(short_video, batch_size=8, fps=10,
                         use_ffmpeg=False, backend='native')
    frames = [f for b, _, _ in loader for f in b]
    ref = VideoLoader(short_video, batch_size=8, fps=10,
                      use_ffmpeg=False, backend='cv2')
    ref_frames = [f for b, _, _ in ref for f in b]
    assert len(frames) == len(ref_frames) > 0
    assert_frames_close(np.stack(frames), np.stack(ref_frames))


def test_prefetch_order_and_completeness():
    items = list(range(100))
    assert list(prefetch(iter(items), depth=3)) == items


def test_prefetch_propagates_exception():
    def gen():
        yield 1
        raise ValueError('decode failed')

    it = prefetch(gen(), depth=2)
    assert next(it) == 1
    with pytest.raises(ValueError, match='decode failed'):
        list(it)


def test_prefetch_early_close():
    """Abandoning the consumer must not deadlock the producer thread."""
    def gen():
        for i in range(10_000):
            yield i

    it = prefetch(gen(), depth=2)
    assert next(it) == 0
    it.close()


def _patch_tkhd_rotation(src: str, dst: str) -> None:
    """Binary-patch the mp4 tkhd display matrix to a 90° cw rotation."""
    import struct

    data = bytearray(open(src, 'rb').read())
    i = data.find(b'tkhd')
    assert i > 0, 'no tkhd box in test clip'
    m = i + 4 + 1 + 3 + 20 + 16  # v0 tkhd: matrix is 44 bytes after fourcc
    data[m:m + 36] = struct.pack(
        '>9i', 0, 0x00010000, 0, -0x00010000, 0, 0, 0, 0, 0x40000000)
    open(dst, 'wb').write(data)


def test_rotation_metadata(short_video, tmp_path):
    """Display-matrix rotation is applied like cv2's auto-rotate.

    Phone portrait videos carry a rotate-90 display matrix; the native
    backend must yield the same upright frames and swapped dims as cv2, or
    backend='auto' silently changes orientation semantics.
    """
    rot = str(tmp_path / 'rot90.mp4')
    _patch_tkhd_rotation(short_video, rot)

    dec = native.NativeFrameDecoder(rot).open()
    assert dec.rotation == 90
    plain = native.NativeFrameDecoder(short_video).open()
    assert plain.rotation == 0
    assert (dec.width, dec.height) == (plain.height, plain.width)
    plain.release()

    nat = [f.copy() for _, f in zip(range(4), (fr for _, fr in dec))]
    cv = [f for _, f in zip(range(4), (fr for _, fr in Cv2FrameDecoder(rot)))]
    if cv[0].shape != nat[0].shape:
        pytest.skip('this cv2 build does not auto-rotate')
    assert_frames_close(np.stack(nat), np.stack(cv))


def test_native_audio_tone_roundtrip(tmp_path):
    """libswresample path: decode + resample a tone wav to mono 16 kHz."""
    import wave

    from video_features_tpu.io import native

    if not native.available():
        pytest.skip('native service unavailable')

    sr_in = 44100
    t = np.arange(int(sr_in * 1.5)) / sr_in
    samples = (np.sin(2 * np.pi * 440 * t) * 0.5 * 32767).astype('<i2')
    path = str(tmp_path / 'tone44k.wav')
    with wave.open(path, 'wb') as f:
        f.setnchannels(1)
        f.setsampwidth(2)
        f.setframerate(sr_in)
        f.writeframes(samples.tobytes())

    data, sr = native.read_audio_native(path, 16000)
    assert sr == 16000
    assert abs(len(data) - 24000) < 50        # 1.5 s at 16 kHz
    spec = np.abs(np.fft.rfft(data[:16000]))
    assert abs(int(np.argmax(spec)) - 440) <= 1   # tone survives resample


def test_native_audio_no_track_raises(tmp_path):
    from video_features_tpu.io import native

    if not native.available():
        pytest.skip('native service unavailable')
    bad = tmp_path / 'not_media.mp4'
    bad.write_bytes(b'\x00' * 128)
    with pytest.raises(IOError):
        native.read_audio_native(str(bad), 16000)


def test_vggish_native_backend_e2e(sample_video, tmp_path):
    """mp4 → features with audio_backend=native (no ffmpeg binary needed)."""
    from video_features_tpu.config import load_config
    from video_features_tpu.io import native
    from video_features_tpu.registry import create_extractor

    if not native.available():
        pytest.skip('native service unavailable')

    args = load_config('vggish', overrides={
        'video_paths': sample_video, 'device': 'cpu',
        'audio_backend': 'native',
        'output_path': str(tmp_path / 'out'), 'tmp_path': str(tmp_path / 'tmp'),
    })
    ex = create_extractor(args)
    out = ex.extract(sample_video)
    feats = out['vggish']
    # the sample clip is ~18 s → 18 examples of 0.96 s
    assert feats.shape[1] == 128 and feats.shape[0] >= 15
    assert np.isfinite(feats).all()


@needs_native
def test_decode_deterministic_odd_width(tmp_path):
    """Repeated decodes must be bit-identical even when width % 8 != 0.

    swscale's SIMD tail paths are alignment-dependent without
    SWS_BITEXACT|SWS_ACCURATE_RND (native/vfdecode.cc ensure_sws); the
    destination numpy chunks land at varying addresses, which silently
    corrupted the last columns differently on every run."""
    import cv2
    path = str(tmp_path / 'odd.mp4')
    w, h = 340, 256  # 340 % 8 == 4 exercises the tail path
    wr = cv2.VideoWriter(path, cv2.VideoWriter_fourcc(*'mp4v'), 25.0, (w, h))
    rng = np.random.RandomState(0)
    for t in range(12):
        frame = (rng.rand(h, w, 3) * 255).astype(np.uint8)
        wr.write(frame)
    wr.release()

    def decode():
        return [f.copy() for _, f in native.NativeFrameDecoder(path)]

    a, b, c = decode(), decode(), decode()
    assert len(a) == 12
    for x, y in ((a, b), (a, c)):
        for fa, fb in zip(x, y):
            np.testing.assert_array_equal(fa, fb)


def _insert_colr_bt709(src: str, dst: str) -> None:
    """Append a bt709 'colr' (nclx) box to the mp4v sample entry and fix
    ancestor box sizes — tags the stream BT.709 without re-encoding."""
    import struct

    data = bytearray(open(src, 'rb').read())

    def walk(buf, start, end, path=()):
        off = start
        while off + 8 <= end:
            size, = struct.unpack('>I', buf[off:off + 4])
            typ = bytes(buf[off + 4:off + 8])
            if size < 8:
                break
            yield path + (typ,), off, size
            if typ in (b'moov', b'trak', b'mdia', b'minf', b'stbl', b'stsd'):
                body = off + 8 + (8 if typ == b'stsd' else 0)
                yield from walk(buf, body, off + size, path + (typ,))
            off += size

    entries = [(o, s) for p, o, s in walk(data, 0, len(data))
               if p[-1] == b'mp4v']
    assert entries, 'no mp4v sample entry found'
    off, size = entries[0]
    colr = (struct.pack('>I', 19) + b'colr' + b'nclx'
            + struct.pack('>HHH', 1, 1, 1) + bytes([0]))
    new = bytearray(data[:off + size]) + colr + data[off + size:]
    for p, o, s in walk(data, 0, len(data)):
        if o <= off < o + s and p[-1] in (b'moov', b'trak', b'mdia', b'minf',
                                          b'stbl', b'stsd', b'mp4v'):
            cur, = struct.unpack('>I', bytes(new[o:o + 4]))
            struct.pack_into('>I', new, o, cur + 19)
    open(dst, 'wb').write(bytes(new))


@needs_native
def test_bt709_tagged_falls_back_and_tracks_cv2(tmp_path):
    """A BT.709-tagged stream must NOT go through the BT.601-fitted
    tables: the guard routes it to the swscale fallback, which honors the
    tagged matrix via sws_setColorspaceDetails (like a metadata-aware
    cv2). On smooth content the fallback sits within ~1 level of cv2
    (swscale-generation + chroma-interpolation rounding); using the 601
    tables here would be off by up to ~20 levels on saturated colors.

    The clip is a smooth gradient (nearest-vs-bilinear chroma
    upsampling, the dominant fallback-vs-cv2 difference, is tiny on
    smooth chroma; on noise it dominates and proves nothing about the
    matrix)."""
    import cv2
    base = str(tmp_path / 'grad.mp4')
    tagged = str(tmp_path / 'grad709.mp4')
    w, h = 64, 48
    wr = cv2.VideoWriter(base, cv2.VideoWriter_fourcc(*'mp4v'), 25, (w, h))
    gx = np.linspace(0, 255, w)[None, :]
    gy = np.linspace(0, 255, h)[:, None]
    for t in range(6):
        f = np.stack([np.broadcast_to(gx, (h, w)),
                      np.broadcast_to(gy, (h, w)),
                      np.full((h, w), 40 * t)], -1).astype(np.uint8)
        wr.write(f)
    wr.release()
    _insert_colr_bt709(base, tagged)

    def decode_both(path):
        nat = [f.copy() for _, f in native.NativeFrameDecoder(path)]
        cv = [f for _, f in Cv2FrameDecoder(path)]
        assert len(nat) == len(cv) > 0
        return np.stack(nat).astype(np.int16), np.stack(cv).astype(np.int16)

    # untagged: the 601 tables, bit-exact on the fitted cv2 build (smooth
    # gradient fixture → the hard per-pixel band applies cross-build too)
    n0, c0 = decode_both(base)
    assert_frames_close(n0, c0, smooth=True)
    # tagged: swscale fallback with 709 coefficients, close to cv2's 709
    n1, c1 = decode_both(tagged)
    d = np.abs(n1 - c1)
    print(f'[bt709] fallback vs cv2: mean {d.mean():.3f} max {int(d.max())}')
    assert d.mean() < 2.5, d.mean()
    # and the tag MATTERS: cv2's own 709 output differs from its 601
    # output, so a guard regression (tables on tagged content) would
    # show up as a much larger native-vs-cv2 delta than asserted above
    assert np.abs(c1 - c0).max() > 5, 'tag had no effect — bad fixture'
