"""ResNet backbones: every registry arch builds and produces its feat_dim."""
import numpy as np
import pytest

from video_features_tpu.models import resnet as resnet_model
from video_features_tpu.transplant.torch2jax import transplant


@pytest.mark.parametrize('arch', list(resnet_model.ARCHS))
def test_forward_shapes_all_archs(arch):
    cfg = resnet_model.ARCHS[arch]
    params = transplant(resnet_model.init_state_dict(arch=arch))
    x = np.random.RandomState(0).rand(1, 64, 64, 3).astype(np.float32)
    feats = np.asarray(resnet_model.forward(params, x, arch=arch))
    assert feats.shape == (1, cfg['feat_dim']), arch
    assert np.isfinite(feats).all()
