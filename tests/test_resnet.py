"""ResNet backbones: every registry arch builds and produces its feat_dim."""
import numpy as np
import pytest

from video_features_tpu.models import resnet as resnet_model
from video_features_tpu.transplant.torch2jax import transplant


@pytest.mark.parametrize('arch', list(resnet_model.ARCHS))
def test_forward_shapes_all_archs(arch):
    cfg = resnet_model.ARCHS[arch]
    params = transplant(resnet_model.init_state_dict(arch=arch))
    x = np.random.RandomState(0).rand(1, 64, 64, 3).astype(np.float32)
    feats = np.asarray(resnet_model.forward(params, x, arch=arch))
    assert feats.shape == (1, cfg['feat_dim']), arch
    assert np.isfinite(feats).all()


@pytest.mark.parametrize(
    'arch', ['resnet18', 'resnet50', 'resnext50_32x4d', 'wide_resnet50_2'])
def test_parity_vs_torch_mirror(arch):
    """Numerics vs a state-dict-compatible torchvision mirror (BasicBlock
    for 18, Bottleneck/V1.5 for 50, grouped/wide bottlenecks for the
    resnext/wide variants) — the nets behind reference
    extract_resnet.py:40 (`models.get_model` accepts them all).
    rel L2 < 1e-3 at float32."""
    import jax
    import torch

    from tests.torch_mirrors import TorchResNet, randomize_bn_stats

    torch.manual_seed(0)
    mirror = TorchResNet(arch).eval()
    randomize_bn_stats(mirror)
    params = transplant(mirror.state_dict())

    x = np.random.RandomState(1).rand(2, 112, 112, 3).astype(np.float32) * 2 - 1
    with torch.no_grad():
        xt = torch.from_numpy(x).permute(0, 3, 1, 2)
        ref = mirror(xt).numpy()
        ref_logits = mirror(xt, features=False).numpy()
    with jax.default_matmul_precision('highest'):
        got = np.asarray(resnet_model.forward(params, x, arch=arch))
        got_logits = np.asarray(
            resnet_model.forward(params, x, arch=arch, features=False))

    for ours, theirs in ((got, ref), (got_logits, ref_logits)):
        rel = np.linalg.norm(ours - theirs) / np.linalg.norm(theirs)
        assert rel < 1e-3, f'{arch}: rel L2 {rel}'
