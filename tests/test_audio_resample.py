"""Kaiser-best resampler: mirror equivalence, fidelity, and the measured
scipy-path divergence.

The production resampler (ops/audio.py:resample_kaiser) is a vectorized
implementation of resampy 0.4.2's windowed-sinc interpolation (the
algorithm behind the reference's ``resampy.resample(data, sr, 16000)``,
reference models/vggish/vggish_src/vggish_input.py:47-49; resampy itself
is not installable here). The first test pins it against a LITERAL
per-sample transcription of resampy's interpn.py loop — deliberately
written with explicit python loops and no shared code with the
vectorized version, so a vectorization bug cannot cancel out.
"""
from __future__ import annotations

import numpy as np
import pytest

from video_features_tpu.ops.audio import (
    SAMPLE_RATE, resample, resample_kaiser, waveform_to_examples,
)


def _resampy_literal(x: np.ndarray, sr_orig: int, sr_new: int) -> np.ndarray:
    """The literal per-sample transcription of resampy's loop, shared
    with the reference-side vggish composition
    (tests/reference_pipeline.py:resample_reference_literal)."""
    from tests.reference_pipeline import resample_reference_literal

    return resample_reference_literal(x, sr_orig, sr_new)


@pytest.mark.parametrize('sr', [44100, 48000, 22050, 8000])
def test_kaiser_matches_literal_transcription(sr):
    """Vectorized production path ≡ the literal loop, all common rates
    (44.1k/48k real mp4 audio, 22.05k, and UPsampling from 8k)."""
    rng = np.random.RandomState(0)
    x = rng.randn(sr // 5).astype(np.float64)     # 200 ms
    got = resample_kaiser(x, sr, SAMPLE_RATE)
    ref = _resampy_literal(x, sr, SAMPLE_RATE)
    assert got.shape == ref.shape
    err = np.max(np.abs(got - ref)) / max(np.max(np.abs(ref)), 1e-30)
    assert err < 1e-12, f'vectorized vs literal at {sr} Hz: {err}'


def test_kaiser_sine_fidelity():
    """A pure in-band tone survives 44.1k→16k essentially intact: the
    kaiser_best filter has a small flat passband gain (~1.003 at this
    ratio — a property of resampy's window normalization, identical in
    the literal transcription), so fit the gain and bound the residual
    distortion, which is what actually corrupts features."""
    sr, f0 = 44100, 440.0
    t = np.arange(sr) / sr                        # 1 s
    x = np.sin(2 * np.pi * f0 * t)
    y = resample_kaiser(x, sr, SAMPLE_RATE)
    t_out = np.arange(y.shape[0]) / SAMPLE_RATE
    mid = slice(2048, -2048)                      # away from edge decay
    basis = np.stack([np.sin(2 * np.pi * f0 * t_out[mid]),
                      np.cos(2 * np.pi * f0 * t_out[mid])], axis=1)
    coef, *_ = np.linalg.lstsq(basis, y[mid], rcond=None)
    gain = float(np.hypot(*coef))
    resid = np.max(np.abs(y[mid] - basis @ coef))
    assert abs(gain - 1) < 5e-3, f'passband gain off: {gain}'
    assert resid < 5e-4, f'in-band distortion: {resid}'


def test_kaiser_length_contract():
    """n_out = n_in * sr_new // sr_orig (resampy ≥0.4.0's integer-floor
    output length) — non-divisible lengths floor, exact-second inputs hit
    the exact sample count."""
    assert resample_kaiser(np.zeros(44100), 44100, 16000).shape == (16000,)
    assert resample_kaiser(np.zeros(44101), 44100, 16000).shape == (16000,)
    assert resample_kaiser(np.zeros(44144), 44100, 16000).shape == (16015,)
    assert resample_kaiser(np.zeros(8000), 8000, 16000).shape == (16000,)


def test_resample_default_is_kaiser():
    """ops.audio.resample routes to the Kaiser path by default (the
    reference-parity resampler is what extraction actually runs)."""
    rng = np.random.RandomState(1)
    x = rng.randn(4410)
    assert np.array_equal(resample(x, 44100), resample_kaiser(x, 44100))


def test_scipy_polyphase_divergence_quantified():
    """The old scipy path differs from the Kaiser path — measured here at
    the FEATURE level (log-mel examples on real-ish audio), so the
    divergence the default path no longer has is a number, not a guess.
    Both resamplers are fed the same 44.1 kHz signal; the examples are
    compared as rel L2. This is documentation-by-test: the assert bounds
    the divergence band (non-zero, sub-percent) rather than a parity bar."""
    rng = np.random.RandomState(2)
    sr = 44100
    t = np.arange(sr * 2) / sr
    x = (0.4 * np.sin(2 * np.pi * (200 + 40 * t) * t)
         + 0.1 * rng.randn(t.shape[0]))
    ex_kaiser = waveform_to_examples(x, sr)
    from video_features_tpu.ops import audio

    data = audio.resample(x, sr, method='polyphase')
    log_mel = audio.log_mel_spectrogram(data, SAMPLE_RATE)
    ex_scipy = audio.frame(
        log_mel, int(round(0.96 * 100)), int(round(0.96 * 100))
    ).astype(np.float32)
    assert ex_kaiser.shape == ex_scipy.shape
    rel = (np.linalg.norm(ex_kaiser - ex_scipy)
           / np.linalg.norm(ex_kaiser))
    print(f'[resample] scipy-vs-kaiser log-mel rel L2: {rel:.3e}')
    assert 0 < rel < 0.05, rel
