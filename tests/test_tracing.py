"""Unit tests for the per-stage tracing subsystem (utils/tracing.py)."""
import threading
import time

import pytest

from video_features_tpu.utils.tracing import (
    NULL_TRACER, Tracer, jax_profiler_trace, merge_reports,
)


def test_stage_accumulates():
    t = Tracer()
    for _ in range(3):
        with t.stage('work'):
            time.sleep(0.001)
    rep = t.report()
    assert rep['work']['count'] == 3
    assert rep['work']['total_s'] >= 0.003
    assert rep['work']['max_s'] <= rep['work']['total_s']


def test_stage_records_on_exception():
    t = Tracer()
    try:
        with t.stage('boom'):
            raise ValueError
    except ValueError:
        pass
    assert t.report()['boom']['count'] == 1


def test_wrap_iter_times_each_next():
    t = Tracer()

    def gen():
        for i in range(4):
            time.sleep(0.001)
            yield i

    assert list(t.wrap_iter('decode', gen())) == [0, 1, 2, 3]
    rep = t.report()
    # 4 yields + the final StopIteration probe
    assert rep['decode']['count'] == 5
    assert rep['decode']['total_s'] >= 0.004


def test_null_tracer_is_noop():
    with NULL_TRACER.stage('x'):
        pass
    assert list(NULL_TRACER.wrap_iter('y', iter([1, 2]))) == [1, 2]
    assert NULL_TRACER.report() == {}


def test_thread_safety():
    t = Tracer()

    def worker():
        for _ in range(200):
            with t.stage('shared'):
                pass

    threads = [threading.Thread(target=worker) for _ in range(4)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    assert t.report()['shared']['count'] == 800


def test_summary_and_reset():
    t = Tracer()
    with t.stage('a'):
        pass
    with t.stage('b'):
        pass
    s = t.summary()
    assert 'a' in s and 'b' in s and 'share' in s
    t.reset()
    assert t.report() == {}
    assert t.summary() == '(no stages recorded)'


def test_merge_reports_occupancy_recombines_from_raw_counts():
    """Aggregate occupancy must recompute from the raw slot counts —
    averaging the per-tracer ratios would weight batches wrongly (a
    1-batch 50% tracer would pull down a 100-batch 95% tracer)."""
    a = Tracer()
    a.add('model', 0.1)
    a.add_occupancy('model', 1, 2)            # 50% over 2 slots
    b = Tracer()
    b.add('model', 0.2)
    b.add_occupancy('model', 95, 100)         # 95% over 100 slots
    merged = merge_reports([a.report(), b.report()])
    m = merged['model']
    assert m['occ_valid'] == 96 and m['occ_capacity'] == 102
    assert m['occupancy'] == pytest.approx(96 / 102)
    # NOT the mean of ratios (0.725)
    assert abs(m['occupancy'] - 0.725) > 0.1
    assert m['count'] == 2
    assert m['total_s'] == pytest.approx(0.3)
    assert m['mean_s'] == pytest.approx(0.15)


def test_merge_reports_first_s_keeps_worst_cold_start():
    """The fleet view's first_s is the WORST cold start across tracers
    (the number an operator sizes warm-up budgets by), and max_s maxes;
    per-tracer ramp is dropped rather than faked."""
    a = Tracer()
    a.add('model', 3.0)                       # cold compile wall
    a.add('model', 0.1)
    b = Tracer()
    b.add('model', 0.5)
    b.add('model', 0.1)
    rep_a, rep_b = a.report(), b.report()
    assert 'ramp' in rep_a['model']
    merged = merge_reports([rep_a, rep_b])
    m = merged['model']
    assert m['first_s'] == pytest.approx(3.0)
    assert m['max_s'] == pytest.approx(3.0)
    assert m['count'] == 4
    assert 'ramp' not in m
    # stages without occupancy never grow occupancy keys
    assert 'occupancy' not in m and 'occ_valid' not in m


def test_merge_reports_disjoint_stages_union():
    a = Tracer()
    a.add('decode', 1.0)
    b = Tracer()
    b.add('save', 2.0)
    merged = merge_reports([a.report(), b.report()])
    assert set(merged) == {'decode', 'save'}
    assert merged['save']['mean_s'] == pytest.approx(2.0)


def test_jax_profiler_trace_none_is_noop():
    with jax_profiler_trace(None):
        pass


def test_jax_profiler_trace_writes(tmp_path):
    import jax
    import jax.numpy as jnp
    with jax_profiler_trace(str(tmp_path)):
        jax.block_until_ready(jnp.ones((8, 8)) @ jnp.ones((8, 8)))
    assert any(tmp_path.rglob('*')), 'profiler wrote nothing'
