"""Unit tests for the per-stage tracing subsystem (utils/tracing.py)."""
import threading
import time

from video_features_tpu.utils.tracing import NULL_TRACER, Tracer, jax_profiler_trace


def test_stage_accumulates():
    t = Tracer()
    for _ in range(3):
        with t.stage('work'):
            time.sleep(0.001)
    rep = t.report()
    assert rep['work']['count'] == 3
    assert rep['work']['total_s'] >= 0.003
    assert rep['work']['max_s'] <= rep['work']['total_s']


def test_stage_records_on_exception():
    t = Tracer()
    try:
        with t.stage('boom'):
            raise ValueError
    except ValueError:
        pass
    assert t.report()['boom']['count'] == 1


def test_wrap_iter_times_each_next():
    t = Tracer()

    def gen():
        for i in range(4):
            time.sleep(0.001)
            yield i

    assert list(t.wrap_iter('decode', gen())) == [0, 1, 2, 3]
    rep = t.report()
    # 4 yields + the final StopIteration probe
    assert rep['decode']['count'] == 5
    assert rep['decode']['total_s'] >= 0.004


def test_null_tracer_is_noop():
    with NULL_TRACER.stage('x'):
        pass
    assert list(NULL_TRACER.wrap_iter('y', iter([1, 2]))) == [1, 2]
    assert NULL_TRACER.report() == {}


def test_thread_safety():
    t = Tracer()

    def worker():
        for _ in range(200):
            with t.stage('shared'):
                pass

    threads = [threading.Thread(target=worker) for _ in range(4)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    assert t.report()['shared']['count'] == 800


def test_summary_and_reset():
    t = Tracer()
    with t.stage('a'):
        pass
    with t.stage('b'):
        pass
    s = t.summary()
    assert 'a' in s and 'b' in s and 'share' in s
    t.reset()
    assert t.report() == {}
    assert t.summary() == '(no stages recorded)'


def test_jax_profiler_trace_none_is_noop():
    with jax_profiler_trace(None):
        pass


def test_jax_profiler_trace_writes(tmp_path):
    import jax
    import jax.numpy as jnp
    with jax_profiler_trace(str(tmp_path)):
        jax.block_until_ready(jnp.ones((8, 8)) @ jnp.ones((8, 8)))
    assert any(tmp_path.rglob('*')), 'profiler wrote nothing'
