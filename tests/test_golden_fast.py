"""FAST-lane flagship golden: the full i3d two-stream composition, reduced.

tests/test_golden_e2e.py holds the full-geometry (T, 2048) golden but runs
only in the slow lane (~10 CPU-minutes); this variant guards the SAME
composition — decode → resize 256 → 17-frame window → RAFT → crop → clamp →
uint8 quantize → both I3D towers → concat → .npy — against the reference
pipeline on every fast-lane run, cut down where the reference's own knobs
allow: one stack (17 frames) and raft_iters=2 (reference
raft_src/raft.py:118 `iters` parameter; spatial geometry cannot shrink —
the reference I3D's fixed avg_pool3d(2,7,7) needs the 224 crop).
"""
import numpy as np
import pytest

from video_features_tpu.config import load_config
from video_features_tpu.registry import create_extractor

REL_L2_TARGET = 1e-3
RAFT_ITERS = 2


@pytest.fixture(scope='module')
def video_17(tmp_path_factory):
    """Exactly one stack_size=16 window (17 frames)."""
    import cv2

    from tests.conftest import REFERENCE_ROOT

    src = REFERENCE_ROOT / 'sample' / 'v_ZNVhz7ctTq0.mp4'
    if not src.exists():
        pytest.skip('sample video unavailable')
    out = str(tmp_path_factory.mktemp('vids17') / 'clip17.mp4')
    cap = cv2.VideoCapture(str(src))
    fps = cap.get(cv2.CAP_PROP_FPS)
    w = int(cap.get(cv2.CAP_PROP_FRAME_WIDTH))
    h = int(cap.get(cv2.CAP_PROP_FRAME_HEIGHT))
    writer = cv2.VideoWriter(out, cv2.VideoWriter_fourcc(*'mp4v'), fps, (w, h))
    for _ in range(17):
        ok, frame = cap.read()
        assert ok
        writer.write(frame)
    cap.release()
    writer.release()
    return out


def test_i3d_two_stream_golden_reduced(reference_repo, video_17, tmp_path):
    from tests.reference_pipeline import (
        build_reference_nets, run_reference_i3d, save_state_dicts,
    )

    nets = build_reference_nets(seed=0)
    ckpts = save_state_dicts(nets, tmp_path / 'ckpts')
    ref = run_reference_i3d(video_17, nets, stack_size=16,
                            raft_iters=RAFT_ITERS)

    args = load_config('i3d', overrides={
        'video_paths': video_17, 'device': 'cpu', 'precision': 'highest',
        'decode_backend': 'cv2', 'stack_size': 16, 'step_size': 16,
        'raft_iters': RAFT_ITERS, 'batch_size': 1,
        'concat_rgb_flow': True, 'on_extraction': 'save_numpy',
        'i3d_rgb_checkpoint_path': ckpts['rgb'],
        'i3d_flow_checkpoint_path': ckpts['flow'],
        'raft_checkpoint_path': ckpts['raft'],
        'output_path': str(tmp_path / 'out'),
        'tmp_path': str(tmp_path / 'tmp'),
    })
    ex = create_extractor(args)
    ex._extract(video_17)                       # the full CLI save path

    from video_features_tpu.utils.output import make_path
    out = np.load(make_path(args.output_path, video_17, 'rgb', '.npy'))

    expected = np.concatenate([ref['rgb'], ref['flow']], axis=-1)
    assert out.shape == expected.shape == (1, 2048)
    rels = {'concat': np.linalg.norm(out - expected)
            / np.linalg.norm(expected)}
    for i, stream in enumerate(('rgb', 'flow')):
        seg = out[:, i * 1024:(i + 1) * 1024]
        rels[stream] = (np.linalg.norm(seg - ref[stream])
                        / np.linalg.norm(ref[stream]))
    print(f'[golden fast] rel L2: {rels}')
    for k, v in rels.items():
        assert v < REL_L2_TARGET, f'{k} rel L2: {rels}'
