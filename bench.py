"""Flagship benchmark: fused I3D two-stream (RAFT-backed) clips/sec/chip.

One stack window (stack_size consecutive frames → RAFT flow → I3D rgb ∥
I3D flow → (2048,) feature) is one "clip" — the unit of the north-star
metric (BASELINE.md: Kinetics-400 val clips/sec/chip). The reference fork's
only timing datapoint is ~4 s/video at stack 16 / step 16 @ 25 fps
(reference Test3.ipynb cells 0,2) ≈ 3.75 clips/s on its unspecified GPU;
``vs_baseline`` is measured against that.

Two rungs, both at a PARITY-GRADE precision (the metric name stamps it):

  * ``e2e`` — video file → decoded frames → device → features, the
    pipeline a user actually runs (native decoder when built, cv2
    otherwise; prefetch + overlapped H2D on).
  * ``ingraph_cli_geom`` — the HEADLINE: the fused graph on
    device-resident batches at the geometry the CLI actually runs
    (short-side-256 decode → 256×340 frames, RAFT over the full padded
    frame, 224 crop in-graph — like the reference pipeline behind its
    3.75 clips/s anecdote), timed INSIDE one jit call (``lax.scan`` over
    distinct input batches, result fetched) — remote-dispatch backends
    can return from ``block_until_ready`` before executing, so only
    value fetches are trustworthy and in-graph iteration amortizes the
    ~100 ms dispatch. A secondary 224² crop-first rung
    (``ingraph_*_224px``) keeps cross-round comparability with the
    round-3/4 headline geometry.

Per-family rungs (s3d / resnet50 / clip / vggish / standalone raft at
native flow resolution — the production steps from
tools/family_precision_study.py) record every BASELINE config's measured
rate in ``rungs`` at the same precision stamp.

The corpus-scale trio: ``worklist_clips_per_sec`` runs the per-video
outer loop over a multi-video worklist (resume contract + prefetch live),
``worklist_packed_clips_per_sec`` runs the SAME worklist batch-major
(``pack_across_videos=true`` — device batches fill across video
boundaries, parallel/packing.py) with the device loop pinned SYNCHRONOUS
(``inflight=1``: D2H after every dispatch), and
``worklist_async_clips_per_sec`` repeats it with the deferred-D2H async
loop (``inflight=2``: batch k-1's readback + scatter + save overlap the
device computing batch k) — the packed/async delta isolates the
readback-overlap win, every rung records its ``inflight`` depth, and
``worklist_packed_batch_occupancy`` records how full the compiled step
actually ran. ``worklist_mesh_clips_per_sec`` repeats the async rung
with the device loop mesh-sharded over N chips (``mesh_devices=N``:
batches plan at capacity × N and shard over the data axis,
parallel/mesh.py) — the pod-scale rung, expected to scale
near-linearly with ``worklist_mesh_devices``.

The serving rung (``serve_*``): the same worklist submitted as dynamic
per-video requests over the warm-pool daemon's socket (serve/) —
sustained warm clips/sec vs the cold-start rate a one-shot CLI pays,
plus p50/p99 request latency and the warm-pool hit rate (asserted > 0,
or the "warm" number is mislabeled). ``BENCH_SERVE=0/1`` overrides the
accelerator-only default.

The cache rung (``cache_*``): the same worklist run twice with the
content-addressed feature cache on (cache/) — cold clips/s with publish
overhead vs warm-hit clips/s (pure O(read) materialization, no decode or
inference), plus per-video hit latency and the store hit rate (asserted
to cover the worklist). ``BENCH_CACHE=0/1`` overrides the
accelerator-only default.

The zero-cold-start rung (``serve_boot_first_feature_s`` /
``serve_boot_first_feature_cold_s`` / ``aot_hit_rate``): boot-to-first-
feature wall time for a pre-warmed daemon (``serve_prewarm`` +
``aot_enabled``, aot/) against a cold vs warm persistent executable
store — the warm boot loads serialized executables instead of compiling
(``builds_compiled == 0`` asserted). ``BENCH_AOT=0/1`` overrides the
accelerator-only default.

The fleet rung (``fleet_warm_clips_per_sec`` / ``fleet_cache_hit_rate``
/ ``fleet_cold_host_first_feature_s`` / ``fleet_metrics_scrape_ms``):
two daemons sharing an L2 feature tier and an AOT artifact tier behind
the content-hash router (fleet/) — host A extracts cold and publishes;
host B boots with empty local stores, pre-warms compile-free off the
artifact tier (``builds_compiled == 0`` asserted), and serves A's
features from the shared L2 without decoding; the warm rate re-serves
the worklist through the router across both hosts, and the scrape
rung times the router's fleet-aggregated ``metrics_prom`` (vft-scope —
the cost of the one-scrape-target design). ``BENCH_FLEET=0/1``
overrides the accelerator-only default.

The precision-ladder rungs (``*_bf16_*`` / ``*_int8_*``): the bf16 fast
lane and the int8 weight lane each get a framewise in-graph rung, a
packed-worklist rung and a serve-warm rung vs their fp32 sibling at
otherwise identical knobs — and EVERY ladder rung records its measured
``*_max_abs_error`` / ``*_rel_l2_error`` beside the speedup (never a
speedup without its cost; the rel-L2 numbers are checkable against the
pinned ``BF16_REL_L2_BOUNDS`` / ``INT8_REL_L2_BOUNDS``). The int8 serve
rung additionally parks the WHOLE ladder — fp32, bf16 and int8 warm
entries — in one daemon (pool size asserted ≥ 3). ``BENCH_BF16`` /
``BENCH_BF16_SERVE`` / ``BENCH_INT8`` / ``BENCH_INT8_SERVE`` override
the accelerator-only defaults.

Default precision is 'mixed' (ops/precision.py): ambient 3-pass bf16 with
the drift-tolerant sub-graphs on 1-pass — measured ≤1e-3 feature drift vs
float32 on the fused path (tools/precision_study.py), i.e. the fastest
setting that still meets the reference-parity bar. BENCH_PRECISION
overrides (e.g. 'highest' for the float32 ladder rung, 'default' for the
no-parity speed ceiling).

The SECOND north-star model, R(2+1)D (BASELINE.md names both), gets its
own in-graph + e2e rungs (``r21d_ingraph_*`` / ``r21d_e2e_*``) at the
same precision stamp; its ladder lives in tools/r21d_precision_study.py
(at 'mixed' the drift vs float32 is 2.0e-4 — parity-grade).

Prints exactly ONE JSON line (all diagnostics — random-weights warnings,
decoder chatter, cache notes — go to stderr). The headline value is the
in-graph rung by policy on this environment (the e2e rung here measures a
remote-TPU tunnel, not the machine — see docs/benchmarks.md); every
measured rung is recorded in ``rungs``, and ``BENCH_MODE=e2e`` promotes
the e2e rung to headline on hosts where the transfer is real PCIe.
"""
from __future__ import annotations

import contextlib
import json
import os
import sys
import time
from pathlib import Path

import numpy as np

# Reference anecdote: ~4 s/video, ~15 stacks/video at stack 16 step 16 @25fps
BASELINE_CLIPS_PER_SEC = 3.75


def bench_ingraph(jax, precision, pins, device, platform, params,
                  stack, h, w, batch, iters):
    """Device-only fused-graph clips/sec (in-graph scan, value fetch) at
    an arbitrary frame geometry.

    The CLI-geometry rung feeds the decode geometry the real pipeline
    produces (short-side 256 → the sample's 256×340; RAFT sees the FULL
    frame padded to /8, crop 224 happens in-graph after flow — reference
    models/i3d/extract_i3d.py:38-62,143-164). The square-224 rung is the
    crop-first ceiling the pipeline never runs; it stays as a secondary
    rung only."""
    import jax.numpy as jnp
    from jax import lax

    from video_features_tpu.extract.i3d import fused_two_stream_step
    from video_features_tpu.models import raft as raft_model

    rng = np.random.RandomState(0)
    # uint8 device residents, cast in-graph: what production ships (the
    # extractors keep frames uint8 until on device), and 4x less HBM for
    # the iters-deep input buffer — the fp32 buffer pushed the v5e-8's
    # 16G HBM over capacity at CLI geometry
    all_stacks = jax.device_put(
        rng.randint(0, 255, size=(iters, batch, stack + 1, h, w, 3))
        .astype(np.uint8), device)
    pads = tuple(raft_model.pad_to_multiple(
        np.zeros((1, h, w, 1), np.float32))[1])
    kwargs = dict(pads=pads, streams=('rgb', 'flow'),
                  crop_size=min(224, h, w), platform=platform, pins=pins)

    def chained(p, xs):
        # per-stream checksums double as the finiteness guard (any NaN/Inf
        # element propagates into its stream's sum) without compiling a
        # second full-graph executable
        def body(acc, stacks):
            with jax.default_matmul_precision(precision):
                o = fused_two_stream_step(p, jnp.asarray(stacks, jnp.float32),
                                          **kwargs)
            return {k: acc[k] + o[k].sum() for k in acc}, None
        acc, _ = lax.scan(
            body, {k: jnp.float32(0) for k in kwargs['streams']}, xs)
        return acc

    jitted = jax.jit(chained)
    warm = jax.tree_util.tree_map(float, jitted(params, all_stacks))
    for s, v in warm.items():                      # compile + warmup + guard
        assert np.isfinite(v), f'{s} checksum not finite'

    t0 = time.perf_counter()
    checksum = jax.tree_util.tree_map(float, jitted(params, all_stacks))
    elapsed = time.perf_counter() - t0             # value fetch = real time
    assert all(np.isfinite(v) for v in checksum.values()), checksum
    return batch * iters / elapsed


def bench_family_ingraph(jax, ambient, device, init_fn, step_fn,
                         batch_shape, input_map, count_per_batch, iters,
                         transplant):
    """One family's device-only in-graph rate (scan + checksum fetch) —
    the shared timing harness for every per-family rung, fed by
    tools/family_precision_study._family_specs so bench.py and the
    precision-ladder tool measure the identical production step."""
    from jax import lax

    params = jax.device_put(transplant(init_fn()), device)
    rng = np.random.RandomState(0)
    raw = rng.randint(0, 255,
                      size=(iters,) + batch_shape).astype(np.float32)
    if input_map is not None:
        raw = input_map(raw).astype(np.float32)
    frames = jax.device_put(raw, device)

    def chained(p, xs):
        def body(acc, batch):
            with jax.default_matmul_precision(ambient):
                return acc + step_fn(p, batch).sum(), None
        acc, _ = lax.scan(body, jax.numpy.float32(0), xs)
        return acc

    jitted = jax.jit(chained)
    assert np.isfinite(float(jitted(params, frames)))   # compile + guard
    t0 = time.perf_counter()
    checksum = float(jitted(params, frames))
    elapsed = time.perf_counter() - t0
    assert np.isfinite(checksum)
    count = (count_per_batch if count_per_batch is not None
             else batch_shape[0])
    return count * iters / elapsed


def bench_serve(precision: str, batch: int, stack: int, tmp_dir: str,
                platform: str, wl_paths: list) -> dict:
    """The serving rung: sustained clips/sec + p50/p99 request latency
    through the warm-pool service (serve/), against the SAME worklist the
    cold-CLI rungs measure.

    Two passes of per-video requests over the live socket: the COLD pass
    pays transplant + compile inside its first request (what a cold CLI
    invocation pays every time); the WARM pass is the steady state a
    resident server actually serves — its pool hit rate must be > 0 or
    the measurement is mislabeled (asserted). Fresh output roots per pass
    keep the resume contract from turning pass 2 into an all-skip no-op.
    """
    from video_features_tpu.serve.client import ServeClient
    from video_features_tpu.serve.server import ExtractionServer
    from video_features_tpu.utils.output import make_path

    base = {
        'device': platform, 'precision': precision,
        'stack_size': stack, 'step_size': stack, 'batch_size': batch,
        'allow_random_weights': True, 'on_extraction': 'save_numpy',
        'tmp_path': os.path.join(tmp_dir, 'serve_tmp'),
    }
    server = ExtractionServer(
        base_overrides=base,
        queue_depth=max(64, 4 * len(wl_paths))).start()
    try:
        client = ServeClient(port=server.port)

        def one_pass(tag):
            out_root = os.path.join(tmp_dir, f'serve_out_{tag}')
            t0 = time.perf_counter()
            # one request per video: dynamic arrivals, packed across
            # requests by the server — NOT one batch-submitted worklist
            rids = [client.submit('i3d', [p],
                                  overrides={'output_path': out_root})
                    for p in wl_paths]
            for rid in rids:
                st = client.wait(rid, timeout_s=900)
                assert st['state'] == 'done', f'serve pass {tag}: {st}'
            return out_root, time.perf_counter() - t0

        _, cold_s = one_pass('cold')
        warm_root, warm_s = one_pass('warm')

        clips = 0
        for p in wl_paths:
            # sanity_check appends <feature_type> to each request's root
            arr = np.load(make_path(os.path.join(warm_root, 'i3d'),
                                    p, 'rgb', '.npy'))
            clips += arr.shape[0]
        assert clips > 0, 'serve warm pass produced no clips'
        m = client.metrics()
        assert m['warm_pool']['hit_rate'] > 0, \
            'warm pass never hit the warm pool — rung mislabeled'
        return {
            'serve_clips_per_sec': round(clips / warm_s, 3),
            'serve_cold_clips_per_sec': round(clips / cold_s, 3),
            'serve_p50_latency_s': m['latency']['p50_s'],
            'serve_p99_latency_s': m['latency']['p99_s'],
            'serve_warm_hit_rate': round(m['warm_pool']['hit_rate'], 4),
        }
    finally:
        server.drain(wait=True, grace_s=120)


def bench_serve_ingress(tmp_dir: str, platform: str,
                        wl_paths: list) -> dict:
    """The ingress rung (ingress/): the HTTP front door's overhead vs
    the loopback socket, plus one real segment query driven through it.

    One resnet segment request goes through the whole network path
    (auth → quota → admission → windower range filter → saved files),
    then the SAME completed request is status-polled N times over each
    surface — ingress ``GET /v1/requests/<id>`` vs loopback ``status``
    — one connection per call on both sides (the ingress speaks one
    request per connection by design, so the loopback comparator must
    pay its connect too or the diff measures connection reuse, not the
    HTTP layer). Reports p50/p99 RTT for both.
    """
    import http.client

    from video_features_tpu.ingress.auth import ApiKeyAuth, Tenant
    from video_features_tpu.ingress.gateway import IngressGateway
    from video_features_tpu.serve.client import ServeClient
    from video_features_tpu.serve.server import ExtractionServer

    base = {
        'device': platform, 'model_name': 'resnet18', 'batch_size': 8,
        'allow_random_weights': True, 'on_extraction': 'save_numpy',
        'tmp_path': os.path.join(tmp_dir, 'ing_tmp'),
        'output_path': os.path.join(tmp_dir, 'ing_out'),
    }
    server = ExtractionServer(base_overrides=base, queue_depth=64).start()
    gateway = IngressGateway(
        server, auth=ApiKeyAuth({'bench': Tenant('bench')})).start()
    try:
        def api(method, path, body=None):
            c = http.client.HTTPConnection('127.0.0.1', gateway.port,
                                           timeout=600)
            c.request(method, path,
                      body=json.dumps(body) if body is not None else None,
                      headers={'Authorization': 'Bearer bench'})
            r = c.getresponse()
            out = json.loads(r.read())
            c.close()
            assert r.status == 200, (r.status, out)
            return out

        # one real segment query end-to-end through the front door
        doc = api('POST', '/v1/extract', {
            'feature_type': 'resnet', 'video_paths': [wl_paths[0]],
            'range': [0.0, 0.4]})
        rid = doc['request_id']
        while api('GET', f'/v1/requests/{rid}')['state'] == 'running':
            time.sleep(0.05)

        n = int(os.environ.get('BENCH_INGRESS_RTT_N', '100'))
        ing_rtts, loop_rtts = [], []
        for _ in range(n):
            t0 = time.perf_counter()
            api('GET', f'/v1/requests/{rid}')
            ing_rtts.append(time.perf_counter() - t0)
        client = ServeClient(port=server.port)
        for _ in range(n):
            t0 = time.perf_counter()
            client.status(rid)
            loop_rtts.append(time.perf_counter() - t0)

        def pct(xs, p):
            return round(float(np.percentile(xs, p)), 6)

        return {
            'serve_ingress_p50_latency_s': pct(ing_rtts, 50),
            'serve_ingress_p99_latency_s': pct(ing_rtts, 99),
            'serve_ingress_loopback_p50_latency_s': pct(loop_rtts, 50),
            'serve_ingress_loopback_p99_latency_s': pct(loop_rtts, 99),
        }
    finally:
        server.drain(wait=True, grace_s=120)


def bench_aot_boot(tmp_dir: str, platform: str, wl_paths: list) -> dict:
    """The zero-cold-start rung (aot/): boot-to-first-feature wall time
    for a pre-warmed daemon (``serve_prewarm`` + ``aot_enabled``)
    against a COLD executable store — the boot pays XLA compiles and
    publishes them — vs a WARM store, where every pre-warmed program
    LOADS (PJRT deserialization) and the boot must be compile-free
    (``builds_compiled == 0`` asserted, or the rung is mislabeled).
    Both numbers cover ExtractionServer construction, pre-warm, and one
    request completing end to end — the latency a deploy/restart
    actually adds before the first feature lands."""
    from video_features_tpu.serve.client import ServeClient
    from video_features_tpu.serve.server import ExtractionServer

    base = {
        'device': platform, 'model_name': 'resnet18', 'batch_size': 8,
        'allow_random_weights': True, 'on_extraction': 'save_numpy',
        'tmp_path': os.path.join(tmp_dir, 'aot_tmp'),
        'aot_enabled': True, 'aot_dir': os.path.join(tmp_dir, 'aot_store'),
    }

    def boot(tag):
        t0 = time.perf_counter()
        server = ExtractionServer(base_overrides=base,
                                  queue_depth=64).start()
        try:
            server.prewarm(['resnet'])
            client = ServeClient(port=server.port)
            rid = client.submit('resnet', [wl_paths[0]], overrides={
                'output_path': os.path.join(tmp_dir, f'aot_out_{tag}')})
            st = client.wait(rid, timeout_s=900)
            assert st['state'] == 'done', f'aot boot {tag}: {st}'
            first_s = time.perf_counter() - t0
            m = client.metrics()
        finally:
            server.drain(wait=True, grace_s=120)
        return first_s, m

    cold_s, _ = boot('cold')
    warm_s, m_warm = boot('warm')
    pool = m_warm['warm_pool']
    assert pool['builds_compiled'] == 0 and pool['builds_loaded'] >= 1, \
        f'warm-store boot was not compile-free — rung mislabeled: {pool}'
    # per-boot program hit rate (the store counters are process-global
    # and would fold the cold boot's misses in): loaded / all programs
    # this boot resolved
    aot = m_warm['aot']
    programs = aot['programs_loaded'] + aot['programs_compiled']
    return {
        'serve_boot_first_feature_s': round(warm_s, 3),
        'serve_boot_first_feature_cold_s': round(cold_s, 3),
        'aot_hit_rate': round(aot['programs_loaded'] / max(programs, 1),
                              4),
    }


def bench_index(tmp_dir: str, platform: str, wl_paths: list) -> dict:
    """The feature-index rung (index/): a daemon with ``index_enabled``
    extracts a small worklist, the ingest worker folds the published
    cache objects in (lag polled to zero), then every indexed row is
    queried back through the loopback ``search`` command. Reports
    sustained queries/sec and recall@10 — the search is EXACT (batched
    matmul + top-k over every shard), so each row's own identity must
    sit in its top-10 at cosine 1.0 and recall pins to 1.0; anything
    less is an indexing bug, not a quality tradeoff."""
    from video_features_tpu.serve.client import ServeClient
    from video_features_tpu.serve.server import ExtractionServer

    cache_dir = os.path.join(tmp_dir, 'index_cache')
    base = {
        'device': platform, 'model_name': 'resnet18', 'batch_size': 8,
        'allow_random_weights': True, 'on_extraction': 'save_numpy',
        'tmp_path': os.path.join(tmp_dir, 'index_tmp'),
        'output_path': os.path.join(tmp_dir, 'index_out'),
        'cache_enabled': True, 'cache_dir': cache_dir,
        'index_enabled': True,
    }
    server = ExtractionServer(base_overrides=base, queue_depth=64).start()
    try:
        client = ServeClient(port=server.port)
        rid = client.submit('resnet', wl_paths[:2])
        st = client.wait(rid, timeout_s=900)
        assert st['state'] == 'done', f'index rung extract: {st}'
        deadline = time.time() + 120
        while True:
            idx = client.index_status()
            if idx['rows_live'] > 0 and idx['ingest_lag_bytes'] == 0:
                break
            assert time.time() < deadline, f'ingest never converged: {idx}'
            time.sleep(0.05)
        # query every indexed row back (bounded) through the loopback
        # command — the full wire + merge path, not just the matmul
        from video_features_tpu.index.service import resolve_index_dir
        from video_features_tpu.index.shards import IndexStore
        store = IndexStore.get(resolve_index_dir(base))
        rows = []
        for arr, _mask, metas in store.shard_views(
                store.group_for('resnet')):
            rows.extend((arr[i], m) for i, m in enumerate(metas)
                        if m is not None)
        n = min(len(rows), int(os.environ.get('BENCH_INDEX_QUERIES',
                                              '32')))
        assert n > 0, 'index rung: no rows indexed'
        self_hits = 0
        t0 = time.perf_counter()
        for vec, m in rows[:n]:
            out = client.search(family='resnet',
                                vector=[float(x) for x in vec], k=10)
            self_hits += any(h['key'] == m['key']
                             and h['t_ms'] == m['t_ms']
                             for h in out['hits'])
        wall = time.perf_counter() - t0
        return {
            'index_queries_per_sec': round(n / wall, 3),
            'index_recall_at_10': round(self_hits / n, 4),
            'index_rows_live': idx['rows_live'],
        }
    finally:
        server.drain(wait=True, grace_s=120)


def bench_fleet(tmp_dir: str, platform: str, wl_paths: list) -> dict:
    """The fleet rung (fleet/): two daemons sharing an L2 feature tier
    and an AOT artifact tier behind the content-hash router
    (fleet/router.py). Host A extracts the worklist cold — compiling
    and publishing executables to the artifact tier and features to
    the L2. Host B then boots with EMPTY local stores: its pre-warm
    must be compile-free (``builds_compiled == 0`` asserted — every
    program pulls from the artifact tier) and its first feature is the
    peer's L2 publish, served without decoding (admission-time
    ``cached`` status asserted). The warm number is the fleet-wide
    re-serve rate through the router, one submit per video so the ring
    spreads them across both hosts — every video must come back
    ``cached`` or the rung is mislabeled."""
    from video_features_tpu.fleet.router import FleetRouter
    from video_features_tpu.serve.client import ServeClient
    from video_features_tpu.serve.server import ExtractionServer
    from video_features_tpu.utils.output import make_path

    shared = os.path.join(tmp_dir, 'fleet_shared')

    def host_overrides(tag):
        return {
            'device': platform, 'model_name': 'resnet18', 'batch_size': 8,
            'allow_random_weights': True, 'on_extraction': 'save_numpy',
            'tmp_path': os.path.join(tmp_dir, f'fleet_tmp_{tag}'),
            'cache_enabled': True,
            'cache_dir': os.path.join(tmp_dir, f'fleet_l1_{tag}'),
            'cache_l2_dir': os.path.join(shared, 'features'),
            'aot_enabled': True,
            'aot_dir': os.path.join(tmp_dir, f'fleet_aot_{tag}'),
            'aot_l2_dir': os.path.join(shared, 'artifacts'),
        }

    host_a = ExtractionServer(base_overrides=host_overrides('a'),
                              queue_depth=64).start()
    host_b = None
    router = None
    try:
        # cold pass: A owns the whole worklist, compiles, publishes
        ca = ServeClient(port=host_a.port)
        rid = ca.submit('resnet', wl_paths, overrides={
            'output_path': os.path.join(tmp_dir, 'fleet_out_cold')})
        st = ca.wait(rid, timeout_s=900)
        assert st['state'] == 'done', f'fleet cold pass: {st}'

        # cold-host boot-to-first-feature: B joins with empty local
        # stores, pulls A's executables (zero compiles) and serves A's
        # first video from the shared L2 with zero decode
        t0 = time.perf_counter()
        host_b = ExtractionServer(base_overrides=host_overrides('b'),
                                  queue_depth=64).start()
        report = host_b.prewarm(['resnet'])
        assert report['errors'] == [], f'fleet cold-host prewarm: {report}'
        cb = ServeClient(port=host_b.port)
        rid_b = cb.submit('resnet', wl_paths[:1], overrides={
            'output_path': os.path.join(tmp_dir, 'fleet_out_boot')})
        st_b = cb.wait(rid_b, timeout_s=300)
        cold_host_s = time.perf_counter() - t0
        assert st_b['state'] == 'done', f'fleet cold host: {st_b}'
        assert st_b['videos'][wl_paths[0]] == 'cached', \
            f'cold host decoded instead of serving the peer L2: {st_b}'
        wm = host_b.metrics()['warm_pool']
        assert wm['builds_compiled'] == 0, \
            f'cold host compiled — artifact tier missed: {wm}'

        # warm fleet pass: one submit per video through the router, so
        # the ring spreads the worklist across both hosts
        router = FleetRouter(
            [f'127.0.0.1:{host_a.port}', f'127.0.0.1:{host_b.port}'],
            port=0, probe_interval_s=30.0).start()
        cr = ServeClient(port=router.port)
        warm_out = os.path.join(tmp_dir, 'fleet_out_warm')
        t0 = time.perf_counter()
        rids = [cr.submit('resnet', [p],
                          overrides={'output_path': warm_out})
                for p in wl_paths]
        for p, r in zip(wl_paths, rids):
            st = cr.wait(r, timeout_s=300)
            assert st['state'] == 'done', f'fleet warm pass: {st}'
            assert st['videos'][p] == 'cached', \
                f'warm pass missed the shared tier — rung mislabeled: {st}'
        warm_s = time.perf_counter() - t0

        # vft-scope: the aggregated scrape is the fleet's one metrics
        # hop — time it end-to-end (scrape both backends under the
        # probe deadline, relabel host=, merge, SLO tick)
        t0 = time.perf_counter()
        prom = cr.metrics_prom()
        scrape_ms = (time.perf_counter() - t0) * 1000.0
        assert 'vft_fleet_routed_total{host=' in prom, \
            'aggregated exposition missing fleet families'
        assert 'vft_slo_latency_burn_rate{window="5m"}' in prom, \
            'aggregated exposition missing SLO gauges'

        clips = 0
        for p in wl_paths:
            arr = np.load(make_path(
                os.path.join(warm_out, 'resnet', 'resnet18'),
                p, 'resnet', '.npy'))
            clips += arr.shape[0]
        assert clips > 0, 'fleet warm pass produced no clips'
        hits = misses = 0
        for srv in (host_a, host_b):
            cst = srv.metrics()['cache']
            hits += cst['hits']
            misses += cst['misses']
        return {
            'fleet_warm_clips_per_sec': round(clips / warm_s, 3),
            'fleet_cache_hit_rate': round(hits / max(1, hits + misses), 4),
            'fleet_cold_host_first_feature_s': round(cold_host_s, 3),
            'fleet_metrics_scrape_ms': round(scrape_ms, 2),
        }
    finally:
        if router is not None:
            router.stop()
        for srv in (host_a, host_b):
            if srv is not None:
                try:
                    srv.drain(wait=True, grace_s=120)
                except Exception:
                    pass


def bench_cache(precision: str, batch: int, stack: int, tmp_dir: str,
                platform: str, wl_paths: list) -> dict:
    """The content-addressed cache rung (cache/): the SAME worklist run
    twice with ``cache_enabled=true`` — the cold pass pays decode +
    inference and publishes, the warm pass materializes every video from
    the store (fresh output root, so the resume contract can't mask the
    measurement). Reports cold vs warm-hit clips/s, the per-video hit
    latency, and the store's hit rate (hits must cover the worklist or
    the rung is mislabeled — asserted)."""
    from video_features_tpu.config import load_config
    from video_features_tpu.registry import create_extractor
    from video_features_tpu.utils.output import make_path

    cache_dir = os.path.join(tmp_dir, 'feature_cache')

    def one_pass(tag):
        args = load_config('i3d', overrides={
            'video_paths': wl_paths,
            'device': platform, 'precision': precision,
            'stack_size': stack, 'step_size': stack, 'batch_size': batch,
            'allow_random_weights': True, 'on_extraction': 'save_numpy',
            'output_path': os.path.join(tmp_dir, f'cache_out_{tag}'),
            'tmp_path': os.path.join(tmp_dir, 'cache_tmp'),
            'cache_enabled': True, 'cache_dir': cache_dir,
        })
        ex = create_extractor(args)
        t0 = time.perf_counter()
        for p in wl_paths:
            ex._extract(p)
        return ex, time.perf_counter() - t0

    ex_cold, cold_s = one_pass('cold')
    ex_warm, warm_s = one_pass('warm')

    clips = 0
    for p in wl_paths:
        arr = np.load(make_path(ex_warm.output_path, p, 'rgb', '.npy'))
        clips += arr.shape[0]
    assert clips > 0, 'cache warm pass produced no clips'
    st = ex_warm.cache.stats()
    assert st['hits'] >= len(wl_paths), \
        f'cache warm pass missed the store — rung mislabeled: {st}'
    return {
        'cache_cold_clips_per_sec': round(clips / cold_s, 3),
        'cache_hit_clips_per_sec': round(clips / warm_s, 3),
        'cache_hit_latency_s': round(warm_s / len(wl_paths), 4),
        'cache_hit_rate': round(st['hit_rate'], 4),
        'cache_bytes_saved': int(st['bytes_saved']),
    }


def _feature_file_errors(root_a: str, root_b: str) -> dict:
    """max-abs + rel-L2 error between two output roots' FEATURE files
    (matched by relative path; fps/timestamps sidecars excluded — they
    are identical across lanes and would dilute the rel-L2 denominator).
    The honest error a *_bf16_* rung records next to its speedup."""
    from video_features_tpu.ops.precision import rel_l2

    def feature_files(root):
        return {p.relative_to(root): p for p in Path(root).rglob('*.npy')
                if not p.name.endswith(('_fps.npy',
                                        '_timestamps_ms.npy'))}

    a_files, b_files = feature_files(root_a), feature_files(root_b)
    # symmetric: an extra/renamed bf16 output is itself a divergence the
    # rung must surface, not silently ignore
    assert set(a_files) == set(b_files), (
        f'lanes produced different output sets: only-fp32='
        f'{sorted(set(a_files) - set(b_files))} only-bf16='
        f'{sorted(set(b_files) - set(a_files))}')
    refs, cands = [], []
    for rel, pa in sorted(a_files.items()):
        refs.append(np.load(pa).ravel())
        cands.append(np.load(b_files[rel]).ravel())
    assert refs, 'no feature files to compare'
    ref = np.concatenate(refs)
    cand = np.concatenate(cands)
    return {
        'max_abs_error': round(float(np.max(np.abs(ref - cand))), 6),
        'rel_l2_error': round(rel_l2(ref, cand), 6),
    }


def bench_bf16_framewise(jax, device, iters: int, on_accel: bool) -> dict:
    """The framewise in-graph bf16 rung: the SAME resnet step (the
    production ``ExtractResNet._forward``) timed fp32 vs bf16 on
    device-resident uint8 batches — bf16 params from the transplant cast
    (half the HBM), bf16 activations with the ops/nn.py fp32 islands —
    plus the measured error of one batch. The framewise families are the
    bandwidth-bound end (2500+ frames/s) where bf16 storage pays most."""
    from functools import partial

    import jax.numpy as jnp
    from jax import lax

    from video_features_tpu.extract.resnet import ExtractResNet
    from video_features_tpu.models import resnet as resnet_model
    from video_features_tpu.ops.precision import param_np_dtype, rel_l2
    from video_features_tpu.transplant.torch2jax import transplant

    arch = 'resnet50' if on_accel else 'resnet18'
    size = 224 if on_accel else 64
    batch = 32 if on_accel else 2
    sd = resnet_model.init_state_dict(arch=arch)
    rng = np.random.RandomState(0)
    frames = jax.device_put(
        rng.randint(0, 255, (iters, batch, size, size, 3))
        .astype(np.uint8), device)
    one = jax.device_put(
        rng.randint(0, 255, (batch, size, size, 3)).astype(np.uint8),
        device)

    rates, outs = {}, {}
    for lane in ('float32', 'bfloat16'):
        params = jax.device_put(
            transplant(sd, dtype=param_np_dtype(lane)), device)
        step = partial(
            ExtractResNet._forward, arch=arch,
            dtype=jnp.bfloat16 if lane == 'bfloat16' else jnp.float32)

        def chained(p, xs):
            def body(acc, x):
                return acc + step(p, x).sum(), None
            acc, _ = lax.scan(body, jnp.float32(0), xs)
            return acc

        jitted = jax.jit(chained)
        assert np.isfinite(float(jitted(params, frames)))  # compile+guard
        t0 = time.perf_counter()
        checksum = float(jitted(params, frames))
        rates[lane] = batch * iters / (time.perf_counter() - t0)
        assert np.isfinite(checksum)
        outs[lane] = np.asarray(jax.jit(step)(params, one))

    err = float(np.max(np.abs(outs['float32'] - outs['bfloat16'])))
    return {
        'resnet_ingraph_bf16_frames_per_sec': round(rates['bfloat16'], 3),
        'resnet_ingraph_bf16_fp32_frames_per_sec': round(
            rates['float32'], 3),
        'resnet_ingraph_bf16_speedup': round(
            rates['bfloat16'] / rates['float32'], 3),
        'resnet_ingraph_bf16_max_abs_error': round(err, 6),
        'resnet_ingraph_bf16_rel_l2_error': round(
            rel_l2(outs['float32'], outs['bfloat16']), 6),
    }


def bench_int8_framewise(jax, device, iters: int, on_accel: bool) -> dict:
    """The framewise in-graph int8 rung: the SAME resnet step timed fp32
    vs the int8 weight lane on device-resident uint8 batches — int8
    params from transplant-time quantization (a QUARTER of the fp32 HBM
    and H2D bytes; ops/quant.py), fp32 activations after the in-graph
    dequant — plus the measured error of one batch, recorded beside the
    speedup so a committed int8 number is checkable against
    ``INT8_REL_L2_BOUNDS``. Weight-only quantization pays in residency
    and transfer, not FLOPs, so the honest expectation on a compute-rich
    chip is speedup ~1.0 with quarter-size params — the error columns
    are the rung's real payload."""
    from functools import partial

    import jax.numpy as jnp
    from jax import lax

    from video_features_tpu.extract.resnet import ExtractResNet
    from video_features_tpu.models import resnet as resnet_model
    from video_features_tpu.ops.precision import param_np_dtype, rel_l2

    from video_features_tpu.transplant.torch2jax import transplant

    arch = 'resnet50' if on_accel else 'resnet18'
    size = 224 if on_accel else 64
    batch = 32 if on_accel else 2
    sd = resnet_model.init_state_dict(arch=arch)
    rng = np.random.RandomState(0)
    frames = jax.device_put(
        rng.randint(0, 255, (iters, batch, size, size, 3))
        .astype(np.uint8), device)
    one = jax.device_put(
        rng.randint(0, 255, (batch, size, size, 3)).astype(np.uint8),
        device)

    rates, outs = {}, {}
    for lane in ('float32', 'int8'):
        params = jax.device_put(
            transplant(sd, dtype=param_np_dtype(lane)), device)
        # int8 lane activates in float32 (compute_jnp_dtype): the only
        # delta vs the fp32 lane is quantized weights + in-graph dequant
        step = partial(ExtractResNet._forward, arch=arch,
                       dtype=jnp.float32)

        def chained(p, xs):
            def body(acc, x):
                return acc + step(p, x).sum(), None
            acc, _ = lax.scan(body, jnp.float32(0), xs)
            return acc

        jitted = jax.jit(chained)
        assert np.isfinite(float(jitted(params, frames)))  # compile+guard
        t0 = time.perf_counter()
        checksum = float(jitted(params, frames))
        rates[lane] = batch * iters / (time.perf_counter() - t0)
        assert np.isfinite(checksum)
        outs[lane] = np.asarray(jax.jit(step)(params, one))

    err = float(np.max(np.abs(outs['float32'] - outs['int8'])))
    return {
        'resnet_ingraph_int8_frames_per_sec': round(rates['int8'], 3),
        'resnet_ingraph_int8_fp32_frames_per_sec': round(
            rates['float32'], 3),
        'resnet_ingraph_int8_speedup': round(
            rates['int8'] / rates['float32'], 3),
        'resnet_ingraph_int8_max_abs_error': round(err, 6),
        'resnet_ingraph_int8_rel_l2_error': round(
            rel_l2(outs['float32'], outs['int8']), 6),
    }


def bench_serve_bf16(precision: str, tmp_dir: str, platform: str,
                     wl_paths: list) -> dict:
    """The serve-warm bf16 rung: the same worklist served twice per lane
    (cold then warm) through ONE daemon — fp32 and bf16 requests build
    DISTINCT warm pool entries (compute_dtype is pool-key relevant;
    asserted via the pool size), and the warm-pass rates give the
    steady-state speedup a resident bf16 entry actually delivers, with
    the measured error of the warm outputs recorded beside it."""
    from video_features_tpu.serve.client import ServeClient
    from video_features_tpu.serve.server import ExtractionServer
    from video_features_tpu.utils.output import make_path

    base = {
        'device': platform, 'precision': precision,
        'model_name': 'resnet18', 'batch_size': 8,
        'allow_random_weights': True, 'on_extraction': 'save_numpy',
        'tmp_path': os.path.join(tmp_dir, 'sbf_tmp'),
    }
    server = ExtractionServer(
        base_overrides=base,
        queue_depth=max(64, 4 * len(wl_paths))).start()
    try:
        client = ServeClient(port=server.port)

        def one_pass(tag, lane):
            out_root = os.path.join(tmp_dir, f'sbf_out_{tag}')
            t0 = time.perf_counter()
            rids = [client.submit('resnet', [p], overrides={
                        'output_path': out_root,
                        'compute_dtype': lane})
                    for p in wl_paths]
            for rid in rids:
                st = client.wait(rid, timeout_s=900)
                assert st['state'] == 'done', f'serve bf16 {tag}: {st}'
            return out_root, time.perf_counter() - t0

        one_pass('f32_cold', 'float32')
        f32_root, f32_s = one_pass('f32_warm', 'float32')
        one_pass('bf16_cold', 'bfloat16')
        bf16_root, bf16_s = one_pass('bf16_warm', 'bfloat16')

        clips = 0
        for p in wl_paths:
            arr = np.load(make_path(os.path.join(bf16_root, 'resnet',
                                                 'resnet18'),
                                    p, 'resnet', '.npy'))
            clips += arr.shape[0]
        assert clips > 0, 'serve bf16 warm pass produced no clips'
        m = client.metrics()
        # distinct warm entries per lane — the pool-key split the knob's
        # 'both' classification promises (never a shared program)
        assert m['warm_pool']['size'] >= 2, m['warm_pool']
        errs = _feature_file_errors(f32_root, bf16_root)
        return {
            'serve_bf16_clips_per_sec': round(clips / bf16_s, 3),
            'serve_bf16_fp32_clips_per_sec': round(clips / f32_s, 3),
            'serve_bf16_speedup': round(f32_s / bf16_s, 3),
            'serve_bf16_max_abs_error': errs['max_abs_error'],
            'serve_bf16_rel_l2_error': errs['rel_l2_error'],
        }
    finally:
        server.drain(wait=True, grace_s=120)


def bench_serve_int8(precision: str, tmp_dir: str, platform: str,
                     wl_paths: list) -> dict:
    """The serve-warm int8 rung, and the full precision ladder resident
    in ONE daemon: fp32, bf16 and int8 requests for the same family
    build THREE distinct warm pool entries (compute_dtype is pool-key
    relevant on every rung of the ladder; asserted via the pool size),
    the int8 warm-pass rate gives the steady-state throughput a resident
    quarter-size entry delivers, and the measured error of the int8 warm
    outputs vs the fp32 warm outputs rides beside it."""
    from video_features_tpu.serve.client import ServeClient
    from video_features_tpu.serve.server import ExtractionServer
    from video_features_tpu.utils.output import make_path

    base = {
        'device': platform, 'precision': precision,
        'model_name': 'resnet18', 'batch_size': 8,
        'allow_random_weights': True, 'on_extraction': 'save_numpy',
        'tmp_path': os.path.join(tmp_dir, 'si8_tmp'),
        'serve_warm_pool_size': 4,      # three lanes must fit warm
    }
    server = ExtractionServer(
        base_overrides=base,
        queue_depth=max(64, 4 * len(wl_paths))).start()
    try:
        client = ServeClient(port=server.port)

        def one_pass(tag, lane):
            out_root = os.path.join(tmp_dir, f'si8_out_{tag}')
            t0 = time.perf_counter()
            rids = [client.submit('resnet', [p], overrides={
                        'output_path': out_root,
                        'compute_dtype': lane})
                    for p in wl_paths]
            for rid in rids:
                st = client.wait(rid, timeout_s=900)
                assert st['state'] == 'done', f'serve int8 {tag}: {st}'
            return out_root, time.perf_counter() - t0

        one_pass('f32_cold', 'float32')
        f32_root, f32_s = one_pass('f32_warm', 'float32')
        one_pass('bf16_cold', 'bfloat16')           # third ladder rung
        one_pass('int8_cold', 'int8')
        int8_root, int8_s = one_pass('int8_warm', 'int8')

        clips = 0
        for p in wl_paths:
            arr = np.load(make_path(os.path.join(int8_root, 'resnet',
                                                 'resnet18'),
                                    p, 'resnet', '.npy'))
            clips += arr.shape[0]
        assert clips > 0, 'serve int8 warm pass produced no clips'
        m = client.metrics()
        # the WHOLE ladder resident at once: three distinct warm entries
        # for one family, one per compute_dtype — the pool-key split
        # extended down to int8 (never a shared program across lanes)
        assert m['warm_pool']['size'] >= 3, m['warm_pool']
        errs = _feature_file_errors(f32_root, int8_root)
        return {
            'serve_int8_clips_per_sec': round(clips / int8_s, 3),
            'serve_int8_fp32_clips_per_sec': round(clips / f32_s, 3),
            'serve_int8_speedup': round(f32_s / int8_s, 3),
            'serve_int8_max_abs_error': errs['max_abs_error'],
            'serve_int8_rel_l2_error': errs['rel_l2_error'],
            'serve_int8_warm_pool_size': m['warm_pool']['size'],
        }
    finally:
        server.drain(wait=True, grace_s=120)


def _bench_video(tmp_dir: str, seconds: str = None) -> str:
    """A local benchmark clip: the reference sample if present, else a
    synthetic one (tools/make_sample_video.py). ``BENCH_VIDEO=synthetic``
    forces the synthetic clip and ``seconds`` (default
    ``BENCH_E2E_SECONDS``) its length — the contract smoke test uses a
    1-stack clip so the e2e path stays cheap on CPU. Also the ONE source
    of clip selection for tools/worklist_bench.py, so the e2e and
    worklist rungs always measure the same content."""
    ref = Path('/root/reference/sample/v_GGSY1Qvo990.mp4')
    if ref.exists() and os.environ.get('BENCH_VIDEO') != 'synthetic':
        return str(ref)
    if seconds is None:
        seconds = os.environ.get('BENCH_E2E_SECONDS', '10')
    out = Path(tmp_dir) / 'synth' / 'sample_moving_pattern.mp4'
    if not out.exists():
        import subprocess
        import sys
        # child fds bypass redirect_stdout — pin the subprocess's stdout to
        # stderr so its 'wrote ...' chatter can't break the one-line contract
        subprocess.run(
            [sys.executable, str(Path(__file__).parent / 'tools' /
                                 'make_sample_video.py'),
             '--out', str(out.parent), '--seconds', seconds, '--fps', '25',
             '--size', '340x256'],
            check=True, stdout=sys.stderr)
    return str(out)


def bench_e2e(precision: str, batch: int, stack: int, tmp_dir: str,
              platform: str, feature_type: str = 'i3d', key: str = 'rgb'):
    """File → features clips/sec through the real extractor (decode,
    prefetch, overlapped H2D, fused device step, feature fetch).
    Returns ``(rate, stage_report)`` — the production Tracer's wall-time
    split over the timed runs rides into the bench record
    (``stage_reports``) so a BENCH_*.json explains its own number."""
    from video_features_tpu.config import load_config
    from video_features_tpu.registry import create_extractor

    video = _bench_video(tmp_dir)
    args = load_config(feature_type, overrides={
        'video_paths': video,
        'device': platform,
        'precision': precision,
        'stack_size': stack, 'step_size': stack,
        'batch_size': batch,
        'allow_random_weights': True,
        'profile': True,           # per-stage Tracer feeds stage_reports
        'on_extraction': 'print',  # extraction only; no disk write timing
        'output_path': os.path.join(tmp_dir, 'out'),
        'tmp_path': os.path.join(tmp_dir, 'tmp'),
    })
    ex = create_extractor(args)
    warm = ex.extract(video)                   # compile + cache warm
    clips = warm[key].shape[0]
    assert clips > 0 and np.isfinite(warm[key]).all()
    ex.tracer.reset()                          # timed runs only
    # median of independent runs: remote tunnels hiccup (a single stalled
    # transfer can triple one run's wall time), and the median is the
    # honest steady-state a user sees
    runs = int(os.environ.get('BENCH_E2E_RUNS', 3))
    rates = []
    for _ in range(runs):
        t0 = time.perf_counter()
        out = ex.extract(video)
        rates.append(clips / (time.perf_counter() - t0))
        assert out[key].shape[0] == clips
    from video_features_tpu.utils.tracing import round_report
    return float(np.median(rates)), round_report(ex.tracer.report())


def run() -> dict:
    """Measure all rungs; returns the one-line record. Anything this (or
    the libraries it calls) prints is expected on stderr only — main()
    enforces that by redirecting stdout around the whole measurement."""
    import tempfile

    import jax

    # Local smoke runs: BENCH_PLATFORM=cpu avoids dialing remote hardware.
    if os.environ.get('BENCH_PLATFORM'):
        jax.config.update('jax_platforms', os.environ['BENCH_PLATFORM'])

    from video_features_tpu.models import i3d as i3d_model
    from video_features_tpu.models import raft as raft_model
    from video_features_tpu.ops.precision import (
        MIXED_AMBIENT, MIXED_PINS,
    )
    from video_features_tpu.transplant.torch2jax import transplant
    from video_features_tpu.utils.device import (
        enable_compilation_cache, jax_device,
    )

    platform = jax.devices()[0].platform
    on_accel = platform != 'cpu'
    # Parity-grade default: 'mixed' meets the ≤1e-3 bar at ~the 3-pass
    # speed (tools/precision_study.py); stamp whatever runs into the metric.
    precision = os.environ.get('BENCH_PRECISION', 'mixed')
    ambient, pins = ((MIXED_AMBIENT, MIXED_PINS) if precision == 'mixed'
                     else (precision, None))
    stack = int(os.environ.get('BENCH_STACK', 16))
    # Headline geometry = what the real CLI runs: short-side-256 decode of
    # the reference sample → 256×340 frames, RAFT on the full padded frame,
    # crop 224 in-graph (VERDICT r4 task 2 — the reference's ~3.75 clips/s
    # anecdote ran THIS geometry, so vs_baseline must too). BENCH_SIZE
    # overrides with a square geometry for smoke runs.
    if os.environ.get('BENCH_SIZE'):
        size = int(os.environ['BENCH_SIZE'])
        cli_h, cli_w = size, size
    else:
        cli_h, cli_w = (256, 340) if on_accel else (64, 86)
    # batch sweep on v5e (lanes lookup): 8 → 26.9, 16 → 28.4, 32 → 28.8
    # clips/s; 16 takes nearly all of the win at half the HBM footprint
    batch = int(os.environ.get('BENCH_BATCH', 16 if on_accel else 1))
    iters = int(os.environ.get('BENCH_ITERS', 8 if on_accel else 2))
    enable_compilation_cache('~/.cache/video_features_tpu/xla', platform)

    device = jax_device(platform)
    params = jax.device_put({
        'rgb': transplant(i3d_model.init_state_dict(modality='rgb')),
        'flow': transplant(i3d_model.init_state_dict(modality='flow')),
        'raft': transplant(raft_model.init_state_dict()),
    }, device)

    rungs = {}
    # a BENCH_SIZE square override is NOT the CLI geometry — don't stamp
    # it as such (the metric name would launder a crop-first number into
    # the reconciled headline)
    headline_key = (f'ingraph_cli_geom_{precision}'
                    if not os.environ.get('BENCH_SIZE')
                    else f'ingraph_{precision}')
    rungs[headline_key] = round(
        bench_ingraph(jax, ambient, pins, device, platform, params,
                      stack, cli_h, cli_w, batch, iters), 3)
    if on_accel and not os.environ.get('BENCH_SIZE'):
        # secondary crop-first ceiling at 224² (the round-3/4 headline
        # geometry, kept for cross-round comparability)
        try:
            rungs[f'ingraph_{precision}_224px'] = round(
                bench_ingraph(jax, ambient, pins, device, platform, params,
                              stack, 224, 224, batch, iters), 3)
        except Exception as e:
            rungs['ingraph_224px_error'] = f'{type(e).__name__}: {e}'

    # Per-family rungs through ONE shared harness (bench_family_ingraph),
    # specs from tools/family_precision_study so bench and ladder tool
    # measure the identical production steps. R(2+1)D is the second
    # north-star model (BASELINE.md; ladder: 'mixed' drift 2.0e-4 ✅ /
    # 'default' 3.1e-3 ✗) and always runs; the remaining BASELINE configs
    # (s3d / resnet50 / clip / vggish + standalone raft at native flow
    # resolution — VERDICT r4 task 6) run on accelerators by default,
    # BENCH_FAMILIES=0/1 overrides.
    sys.path.insert(0, str(Path(__file__).parent))
    from tools.family_precision_study import _family_specs
    all_families = (os.environ.get('BENCH_FAMILIES',
                                   '1' if on_accel else '0') == '1')
    for fam, spec in _family_specs(on_accel).items():
        if fam != 'r21d' and not all_families:
            continue
        try:
            init_fn, step_fn, bshape, unit, imap, count = spec
            key = (f'r21d_ingraph_{precision}' if fam == 'r21d' else
                   f'{fam}_ingraph_{precision}_{unit.split("/")[0]}')
            rungs[key] = round(
                bench_family_ingraph(jax, ambient, device, init_fn,
                                     step_fn, bshape, imap, count, iters,
                                     transplant), 3)
        except Exception as e:
            rungs[f'{fam}_ingraph_error'] = f'{type(e).__name__}: {e}'

    # the bf16 fast lane (compute_dtype=bfloat16, ops/precision.py):
    # device-only framewise speedup + measured error vs the fp32
    # sibling, always recorded together so a committed bf16 number is
    # checkable against its family's pinned bound. BENCH_BF16=0/1
    # overrides the accelerator-only default.
    run_bf16 = os.environ.get('BENCH_BF16',
                              '1' if on_accel else '0') == '1'
    if run_bf16:
        try:
            rungs.update(bench_bf16_framewise(jax, device, iters,
                                              on_accel))
        except Exception as e:
            rungs['bf16_ingraph_error'] = f'{type(e).__name__}: {e}'

    # the int8 weight lane (compute_dtype=int8, ops/quant.py): same
    # shape as the bf16 rung — speedup AND measured error, always
    # together, checkable against INT8_REL_L2_BOUNDS. BENCH_INT8=0/1
    # overrides the accelerator-only default.
    run_int8 = os.environ.get('BENCH_INT8',
                              '1' if on_accel else '0') == '1'
    if run_int8:
        try:
            rungs.update(bench_int8_framewise(jax, device, iters,
                                              on_accel))
        except Exception as e:
            rungs['int8_ingraph_error'] = f'{type(e).__name__}: {e}'

    # per-rung Tracer stage reports (decode/h2d/model/save split) ride
    # along in the record so tools/bench_diff.py users can see WHERE a
    # regression landed, not just that one did
    stage_reports = {}
    mode = os.environ.get('BENCH_MODE', 'both' if on_accel else 'ingraph')
    if mode in ('both', 'e2e'):
        with tempfile.TemporaryDirectory() as tmp_dir:
            try:
                rate, rep = bench_e2e(precision, min(batch, 8), stack,
                                      tmp_dir, platform)
                rungs[f'e2e_{precision}'] = round(rate, 3)
                stage_reports[f'e2e_{precision}'] = rep
            except Exception as e:
                rungs['e2e_error'] = f'{type(e).__name__}: {e}'
            try:
                rate, rep = bench_e2e(precision, min(batch, 8), stack,
                                      tmp_dir, platform,
                                      feature_type='r21d', key='r21d')
                rungs[f'r21d_e2e_{precision}'] = round(rate, 3)
                stage_reports[f'r21d_e2e_{precision}'] = rep
            except Exception as e:
                rungs['r21d_e2e_error'] = f'{type(e).__name__}: {e}'
            # Sustained multi-video worklist (resume contract + prefetch
            # + decode overlap live — the corpus-scale number, VERDICT r4
            # task 5); BENCH_WORKLIST=0/1 overrides.
            wl_paths = None
            # the family the worklist trio measures: i3d (the flagship)
            # by default; CPU smoke lanes (contract tests, the CI
            # bench-diff job) override to a cheap family so the rung
            # KEYS stay exercised without paying RAFT-on-CPU minutes
            wl_feature = os.environ.get('BENCH_WORKLIST_FEATURE', 'i3d')
            if os.environ.get('BENCH_WORKLIST',
                              '1' if on_accel else '0') == '1':
                try:
                    from tools.worklist_bench import (
                        make_worklist, run_worklist,
                    )
                    wl_paths = make_worklist(tmp_dir, 4 if on_accel else 2,
                                             10 if on_accel else 2)
                    wrec = run_worklist(wl_feature, wl_paths, tmp_dir,
                                        tmp_dir, platform,
                                        batch_size=min(batch, 8),
                                        stack=stack, precision=precision)
                    rungs[f'worklist_videos_per_min_{precision}'] = \
                        wrec['videos_per_min']
                    rungs[f'worklist_clips_per_sec_{precision}'] = \
                        wrec['clips_per_sec']
                    stage_reports[f'worklist_{precision}'] = wrec['stages']
                except Exception as e:
                    rungs['worklist_error'] = f'{type(e).__name__}: {e}'
                # The SAME worklist object, batch-major
                # (pack_across_videos=true): batches fill across video
                # boundaries (parallel/packing.py) so the compiled step
                # stops running padded tails per video — measured in the
                # same session, with its own output root (the unpacked
                # pass's files would otherwise make it an all-skip no-op).
                # inflight=1 pins the SYNCHRONOUS device loop so the
                # async rung below is a clean A/B over one knob.
                if wl_paths is not None:
                    try:
                        # decode_workers=1 pins the input side in-process
                        # (single decode process) so the packed → async →
                        # farm ladder attributes each delta to one knob
                        wrec_packed = run_worklist(
                            wl_feature, wl_paths,
                            os.path.join(tmp_dir, 'packed'),
                            tmp_dir, platform, batch_size=min(batch, 8),
                            stack=stack, precision=precision, packed=True,
                            inflight=1, decode_workers=1)
                        rungs[f'worklist_packed_clips_per_sec_{precision}'] \
                            = wrec_packed['clips_per_sec']
                        rungs['worklist_packed_inflight'] = \
                            wrec_packed['inflight']
                        stage_reports[f'worklist_packed_{precision}'] = \
                            wrec_packed['stages']
                        if wrec_packed.get('batch_occupancy') is not None:
                            rungs['worklist_packed_batch_occupancy'] = \
                                wrec_packed['batch_occupancy']
                    except Exception as e:
                        rungs['worklist_packed_error'] = \
                            f'{type(e).__name__}: {e}'
                # The async device loop (inflight=2): packed_step only
                # dispatches, D2H + scatter + save of batch k-1 overlap
                # the device computing batch k (parallel/packing.py) —
                # same worklist, own output root, byte-identical outputs
                # (tests/test_packing.py pins parity); the delta vs the
                # inflight=1 rung above is the deferred-readback win.
                if wl_paths is not None:
                    try:
                        wrec_async = run_worklist(
                            wl_feature, wl_paths,
                            os.path.join(tmp_dir, 'async'),
                            tmp_dir, platform, batch_size=min(batch, 8),
                            stack=stack, precision=precision, packed=True,
                            inflight=2, decode_workers=1)
                        rungs[f'worklist_async_clips_per_sec_{precision}'] \
                            = wrec_async['clips_per_sec']
                        rungs['worklist_async_inflight'] = \
                            wrec_async['inflight']
                        stage_reports[f'worklist_async_{precision}'] = \
                            wrec_async['stages']
                        if wrec_async.get('batch_occupancy') is not None:
                            rungs['worklist_async_batch_occupancy'] = \
                                wrec_async['batch_occupancy']
                    except Exception as e:
                        rungs['worklist_async_error'] = \
                            f'{type(e).__name__}: {e}'
                # The decode farm (farm/): same worklist, same async
                # loop, but decode runs in N worker PROCESSES feeding
                # the packer over shared-memory rings — the full
                # pipeline, and the rung the host-decode wall shows up
                # on. Outputs stay byte-identical (tests/test_farm.py);
                # the delta vs the async rung is the farm's win.
                if wl_paths is not None:
                    try:
                        from tools.worklist_bench import \
                            bench_decode_workers
                        n_decode = bench_decode_workers(on_accel)
                        wrec_farm = run_worklist(
                            wl_feature, wl_paths,
                            os.path.join(tmp_dir, 'farm'),
                            tmp_dir, platform, batch_size=min(batch, 8),
                            stack=stack, precision=precision, packed=True,
                            inflight=2, decode_workers=n_decode)
                        rungs[f'worklist_farm_clips_per_sec_{precision}'] \
                            = wrec_farm['clips_per_sec']
                        rungs['worklist_farm_decode_workers'] = \
                            wrec_farm['decode_workers']
                        stage_reports[f'worklist_farm_{precision}'] = \
                            wrec_farm['stages']
                        if wrec_farm.get('batch_occupancy') is not None:
                            rungs['worklist_farm_batch_occupancy'] = \
                                wrec_farm['batch_occupancy']
                    except Exception as e:
                        rungs['worklist_farm_error'] = \
                            f'{type(e).__name__}: {e}'
                # The mesh rung (parallel/mesh.py): same async loop,
                # same in-process decode, but the packed batches plan at
                # capacity × N and shard over the data axis of an
                # N-chip mesh — serve/worklist throughput should scale
                # near-linearly with N, with byte-identical outputs
                # (tests/test_mesh_packed.py pins parity). On a
                # single-device host the rung runs at N=1 and the
                # worklist_mesh_devices metadata says so; CPU CI forces
                # 2 virtual host devices to exercise the sharded path.
                if wl_paths is not None:
                    try:
                        from tools.worklist_bench import bench_mesh_devices
                        wrec_mesh = run_worklist(
                            wl_feature, wl_paths,
                            os.path.join(tmp_dir, 'mesh'),
                            tmp_dir, platform, batch_size=min(batch, 8),
                            stack=stack, precision=precision, packed=True,
                            inflight=2, decode_workers=1,
                            mesh_devices=bench_mesh_devices())
                        rungs[f'worklist_mesh_clips_per_sec_{precision}'] \
                            = wrec_mesh['clips_per_sec']
                        rungs['worklist_mesh_devices'] = \
                            wrec_mesh['mesh_devices']
                        stage_reports[f'worklist_mesh_{precision}'] = \
                            wrec_mesh['stages']
                        if wrec_mesh.get('batch_occupancy') is not None:
                            rungs['worklist_mesh_batch_occupancy'] = \
                                wrec_mesh['batch_occupancy']
                    except Exception as e:
                        rungs['worklist_mesh_error'] = \
                            f'{type(e).__name__}: {e}'
                # The bf16 fast-lane rung (compute_dtype=bfloat16): the
                # same worklist, packed, on an accepting family
                # (BENCH_BF16_FEATURE, default resnet — the framewise
                # bandwidth-bound end) — one fp32 sibling pass + one
                # bf16 pass at OTHERWISE IDENTICAL knobs (inflight=1,
                # in-process decode), so the delta is the lane alone,
                # with the measured output error recorded next to the
                # speedup (never a speedup without its cost).
                if wl_paths is not None and run_bf16:
                    try:
                        bf_feature = os.environ.get('BENCH_BF16_FEATURE',
                                                    'resnet')
                        wrec_f32 = run_worklist(
                            bf_feature, wl_paths,
                            os.path.join(tmp_dir, 'bf16_f32'),
                            tmp_dir, platform, batch_size=min(batch, 8),
                            stack=stack, precision=precision,
                            packed=True, inflight=1, decode_workers=1,
                            compute_dtype='float32')
                        wrec_bf16 = run_worklist(
                            bf_feature, wl_paths,
                            os.path.join(tmp_dir, 'bf16'),
                            tmp_dir, platform, batch_size=min(batch, 8),
                            stack=stack, precision=precision,
                            packed=True, inflight=1, decode_workers=1,
                            compute_dtype='bfloat16')
                        errs = _feature_file_errors(
                            os.path.join(tmp_dir, 'bf16_f32', 'out'),
                            os.path.join(tmp_dir, 'bf16', 'out'))
                        rungs['worklist_packed_bf16_clips_per_sec'] = \
                            wrec_bf16['clips_per_sec']
                        rungs['worklist_packed_bf16_fp32_clips_per_sec'] \
                            = wrec_f32['clips_per_sec']
                        rungs['worklist_packed_bf16_speedup'] = round(
                            wrec_bf16['clips_per_sec']
                            / max(wrec_f32['clips_per_sec'], 1e-9), 3)
                        rungs['worklist_packed_bf16_max_abs_error'] = \
                            errs['max_abs_error']
                        rungs['worklist_packed_bf16_rel_l2_error'] = \
                            errs['rel_l2_error']
                        rungs['worklist_bf16_compute_dtype'] = \
                            wrec_bf16['compute_dtype']
                        stage_reports['worklist_packed_bf16'] = \
                            wrec_bf16['stages']
                    except Exception as e:
                        rungs['worklist_bf16_error'] = \
                            f'{type(e).__name__}: {e}'
                # The int8 weight-lane rung (compute_dtype=int8): the
                # same packed worklist, one fp32 sibling pass + one int8
                # pass at OTHERWISE IDENTICAL knobs, so the delta is the
                # lane alone — quarter-size params + in-graph dequant —
                # with the measured output error recorded next to the
                # speedup (never a speedup without its cost).
                if wl_paths is not None and run_int8:
                    try:
                        i8_feature = os.environ.get('BENCH_INT8_FEATURE',
                                                    'resnet')
                        wrec_f32 = run_worklist(
                            i8_feature, wl_paths,
                            os.path.join(tmp_dir, 'int8_f32'),
                            tmp_dir, platform, batch_size=min(batch, 8),
                            stack=stack, precision=precision,
                            packed=True, inflight=1, decode_workers=1,
                            compute_dtype='float32')
                        wrec_i8 = run_worklist(
                            i8_feature, wl_paths,
                            os.path.join(tmp_dir, 'int8'),
                            tmp_dir, platform, batch_size=min(batch, 8),
                            stack=stack, precision=precision,
                            packed=True, inflight=1, decode_workers=1,
                            compute_dtype='int8')
                        errs = _feature_file_errors(
                            os.path.join(tmp_dir, 'int8_f32', 'out'),
                            os.path.join(tmp_dir, 'int8', 'out'))
                        rungs['worklist_packed_int8_clips_per_sec'] = \
                            wrec_i8['clips_per_sec']
                        rungs['worklist_packed_int8_fp32_clips_per_sec'] \
                            = wrec_f32['clips_per_sec']
                        rungs['worklist_packed_int8_speedup'] = round(
                            wrec_i8['clips_per_sec']
                            / max(wrec_f32['clips_per_sec'], 1e-9), 3)
                        rungs['worklist_packed_int8_max_abs_error'] = \
                            errs['max_abs_error']
                        rungs['worklist_packed_int8_rel_l2_error'] = \
                            errs['rel_l2_error']
                        rungs['worklist_int8_compute_dtype'] = \
                            wrec_i8['compute_dtype']
                        stage_reports['worklist_packed_int8'] = \
                            wrec_i8['stages']
                    except Exception as e:
                        rungs['worklist_int8_error'] = \
                            f'{type(e).__name__}: {e}'
                # The fused multi-family rung (features=[...]): ONE
                # decode + ONE sha256 pass per video feeding N families
                # (run_packed_fused) vs N sequential per-family passes —
                # the wall-clock speedup plus the decode / hash
                # amortization ratios behind it (both → N when decode
                # dominates). Outputs are byte-parity-checked against
                # the sequential passes before any rate is recorded.
                # BENCH_FUSED=0/1 overrides; BENCH_FUSED_FEATURES picks
                # the family set (default resnet,clip,timm).
                if wl_paths is not None and os.environ.get(
                        'BENCH_FUSED', '1' if on_accel else '0') == '1':
                    try:
                        from tools.worklist_bench import (
                            bench_fused_features, run_worklist_fused,
                        )
                        frec = run_worklist_fused(
                            bench_fused_features(), wl_paths,
                            os.path.join(tmp_dir, 'fused'), tmp_dir,
                            platform, batch_size=min(batch, 8),
                            precision=precision)
                        rungs[f'worklist_fused_clips_per_sec_'
                              f'{precision}'] = frec['clips_per_sec']
                        rungs['worklist_fused_speedup'] = \
                            frec['fused_speedup']
                        rungs['worklist_fused_decode_amortization'] = \
                            frec['decode_amortization']
                        rungs['worklist_fused_hash_amortization'] = \
                            frec['hash_amortization']
                        # which family set produced the number — config
                        # metadata, never gated
                        rungs['worklist_fused_families'] = \
                            ','.join(frec['families'])
                        stage_reports[f'worklist_fused_{precision}'] = \
                            frec['stages']
                    except Exception as e:
                        rungs['worklist_fused_error'] = \
                            f'{type(e).__name__}: {e}'
            # The serving rung (serve/): the same worklist content
            # submitted as dynamic per-video requests against the
            # warm-pool daemon — sustained warm clips/sec, the cold-start
            # rate a one-shot CLI pays, and request-latency percentiles.
            # Independent of BENCH_WORKLIST (it builds its own worklist
            # when that rung was skipped); BENCH_SERVE=0/1 overrides.
            if os.environ.get('BENCH_SERVE',
                              '1' if on_accel else '0') == '1':
                try:
                    if wl_paths is None:
                        from tools.worklist_bench import make_worklist
                        wl_paths = make_worklist(
                            tmp_dir, 4 if on_accel else 2,
                            10 if on_accel else 2)
                    srec = bench_serve(precision, min(batch, 8), stack,
                                       tmp_dir, platform, wl_paths)
                    rungs[f'serve_clips_per_sec_{precision}'] = \
                        srec['serve_clips_per_sec']
                    rungs[f'serve_cold_clips_per_sec_{precision}'] = \
                        srec['serve_cold_clips_per_sec']
                    rungs['serve_p50_latency_s'] = \
                        srec['serve_p50_latency_s']
                    rungs['serve_p99_latency_s'] = \
                        srec['serve_p99_latency_s']
                    rungs['serve_warm_hit_rate'] = \
                        srec['serve_warm_hit_rate']
                except Exception as e:
                    rungs['serve_error'] = f'{type(e).__name__}: {e}'
            # The zero-cold-start rung (aot/): boot-to-first-feature
            # for a pre-warmed daemon against a cold vs warm persistent
            # executable store — the warm boot must be compile-free.
            # BENCH_AOT=0/1 overrides the accelerator-only default.
            if os.environ.get('BENCH_AOT',
                              '1' if on_accel else '0') == '1':
                try:
                    if wl_paths is None:
                        from tools.worklist_bench import make_worklist
                        wl_paths = make_worklist(
                            tmp_dir, 4 if on_accel else 2,
                            10 if on_accel else 2)
                    rungs.update(bench_aot_boot(tmp_dir, platform,
                                                wl_paths))
                except Exception as e:
                    rungs['serve_aot_error'] = f'{type(e).__name__}: {e}'
            # The ingress rung (ingress/): the HTTP front door's RTT
            # percentiles vs the loopback socket, through one real
            # segment query. BENCH_INGRESS=0/1 overrides.
            if os.environ.get('BENCH_INGRESS',
                              '1' if on_accel else '0') == '1':
                try:
                    if wl_paths is None:
                        from tools.worklist_bench import make_worklist
                        wl_paths = make_worklist(
                            tmp_dir, 4 if on_accel else 2,
                            10 if on_accel else 2)
                    irec = bench_serve_ingress(tmp_dir, platform, wl_paths)
                    rungs.update(irec)
                except Exception as e:
                    rungs['serve_ingress_error'] = \
                        f'{type(e).__name__}: {e}'
            # The content-addressed cache rung (cache/): cold extraction
            # vs warm O(read) hits over the same worklist — the dedupe
            # win a corpus with repeated/duplicated videos sees per
            # repeat. BENCH_CACHE=0/1 overrides.
            if os.environ.get('BENCH_CACHE',
                              '1' if on_accel else '0') == '1':
                try:
                    if wl_paths is None:
                        from tools.worklist_bench import make_worklist
                        wl_paths = make_worklist(
                            tmp_dir, 4 if on_accel else 2,
                            10 if on_accel else 2)
                    crec = bench_cache(precision, min(batch, 8), stack,
                                       tmp_dir, platform, wl_paths)
                    rungs[f'cache_cold_clips_per_sec_{precision}'] = \
                        crec['cache_cold_clips_per_sec']
                    rungs[f'cache_hit_clips_per_sec_{precision}'] = \
                        crec['cache_hit_clips_per_sec']
                    rungs['cache_hit_latency_s'] = \
                        crec['cache_hit_latency_s']
                    rungs['cache_hit_rate'] = crec['cache_hit_rate']
                    rungs['cache_bytes_saved'] = crec['cache_bytes_saved']
                except Exception as e:
                    rungs['cache_error'] = f'{type(e).__name__}: {e}'
            # The feature-index rung (index/): serve-side ingest to lag
            # zero, then every row queried back over the loopback search
            # command — queries/sec plus recall@10, which exact search
            # pins to 1.0. BENCH_INDEX=0/1 overrides.
            if os.environ.get('BENCH_INDEX',
                              '1' if on_accel else '0') == '1':
                try:
                    if wl_paths is None:
                        from tools.worklist_bench import make_worklist
                        wl_paths = make_worklist(
                            tmp_dir, 4 if on_accel else 2,
                            10 if on_accel else 2)
                    rungs.update(bench_index(tmp_dir, platform, wl_paths))
                except Exception as e:
                    rungs['index_error'] = f'{type(e).__name__}: {e}'
            # The fleet rung (fleet/): two daemons sharing an L2 feature
            # tier + AOT artifact tier behind the content-hash router —
            # compile-free cold-host boot, peer-published warm serves.
            # BENCH_FLEET=0/1 overrides the accelerator-only default.
            if os.environ.get('BENCH_FLEET',
                              '1' if on_accel else '0') == '1':
                try:
                    if wl_paths is None:
                        from tools.worklist_bench import make_worklist
                        wl_paths = make_worklist(
                            tmp_dir, 4 if on_accel else 2,
                            10 if on_accel else 2)
                    rungs.update(bench_fleet(tmp_dir, platform, wl_paths))
                except Exception as e:
                    rungs['fleet_error'] = f'{type(e).__name__}: {e}'
            # The serve-warm bf16 rung: fp32 and bf16 entries resident
            # side by side in ONE daemon (distinct pool keys), warm
            # rates + measured error. BENCH_BF16_SERVE=0/1 overrides.
            if os.environ.get('BENCH_BF16_SERVE',
                              '1' if on_accel else '0') == '1':
                try:
                    if wl_paths is None:
                        from tools.worklist_bench import make_worklist
                        wl_paths = make_worklist(
                            tmp_dir, 4 if on_accel else 2,
                            10 if on_accel else 2)
                    rungs.update(bench_serve_bf16(precision, tmp_dir,
                                                  platform, wl_paths))
                except Exception as e:
                    rungs['serve_bf16_error'] = f'{type(e).__name__}: {e}'
            # The serve-warm int8 rung + the full ladder in one daemon:
            # fp32/bf16/int8 as three resident pool entries, int8 warm
            # rate + measured error. BENCH_INT8_SERVE=0/1 overrides.
            if os.environ.get('BENCH_INT8_SERVE',
                              '1' if on_accel else '0') == '1':
                try:
                    if wl_paths is None:
                        from tools.worklist_bench import make_worklist
                        wl_paths = make_worklist(
                            tmp_dir, 4 if on_accel else 2,
                            10 if on_accel else 2)
                    rungs.update(bench_serve_int8(precision, tmp_dir,
                                                  platform, wl_paths))
                except Exception as e:
                    rungs['serve_int8_error'] = f'{type(e).__name__}: {e}'
    if mode == 'e2e' and f'e2e_{precision}' in rungs:
        headline_key = f'e2e_{precision}'

    # Headline = the in-graph rung: on this environment's remote-TPU
    # tunnel the e2e rung is transfer-bound at any precision (~20-50 MB/s
    # shared link; see docs/benchmarks.md "End-to-end ... measurement
    # environment") — it is recorded in `rungs` with that caveat, and
    # BENCH_MODE=e2e promotes it on hosts where the transfer is real PCIe.
    value = rungs[headline_key]
    return {
        'metric': f'i3d_two_stream_{headline_key}_clips_per_sec_'
                  f'{platform}_stack{stack}_{cli_h}x{cli_w}',
        'value': value,
        'unit': 'clips/sec/chip',
        'vs_baseline': round(value / BASELINE_CLIPS_PER_SEC, 3),
        'rungs': rungs,
        # rung name → per-stage Tracer report for every instrumented rung
        # (empty dict on in-graph-only runs)
        'stage_reports': stage_reports,
    }


def main() -> None:
    # The driver contract: stdout carries exactly ONE JSON line. Libraries
    # along the e2e path print diagnostics (random-weights warnings,
    # cv2/ffmpeg chatter, cache notes) — shunt ALL of it to stderr and emit
    # the record on the real stdout afterwards.
    stdout = sys.stdout
    with contextlib.redirect_stdout(sys.stderr):
        record = run()
    print(json.dumps(record), file=stdout)


if __name__ == '__main__':
    main()
