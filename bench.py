"""Flagship benchmark: fused I3D two-stream (RAFT-backed) clips/sec/chip.

One stack window (stack_size consecutive frames → RAFT flow → I3D rgb ∥
I3D flow → (2048,) feature) is one "clip" — the unit of the north-star
metric (BASELINE.md: Kinetics-400 val clips/sec/chip). The reference fork's
only timing datapoint is ~4 s/video at stack 16 / step 16 @ 25 fps
(reference Test3.ipynb cells 0,2) ≈ 3.75 clips/s on its unspecified GPU;
``vs_baseline`` is measured against that.

Methodology: the timing loop runs INSIDE one jit call (``lax.scan`` over
``iters`` distinct input batches) and the result is fetched to the host.
Remote-dispatch backends can return from ``block_until_ready`` before the
device has actually executed, and pay ~100 ms per dispatch — only a value
fetch is trustworthy, and in-graph iteration amortizes the dispatch.

Prints exactly ONE JSON line:
    {"metric": ..., "value": N, "unit": "clips/sec/chip", "vs_baseline": N}
"""
from __future__ import annotations

import json
import os
import time

import numpy as np

# Reference anecdote: ~4 s/video, ~15 stacks/video at stack 16 step 16 @25fps
BASELINE_CLIPS_PER_SEC = 3.75


def main() -> None:
    import jax
    import jax.numpy as jnp
    from jax import lax

    # Local smoke runs: BENCH_PLATFORM=cpu avoids dialing remote hardware.
    if os.environ.get('BENCH_PLATFORM'):
        jax.config.update('jax_platforms', os.environ['BENCH_PLATFORM'])

    from video_features_tpu.extract.i3d import fused_two_stream_step
    from video_features_tpu.models import i3d as i3d_model
    from video_features_tpu.models import raft as raft_model
    from video_features_tpu.transplant.torch2jax import transplant
    from video_features_tpu.utils.device import jax_device

    platform = jax.devices()[0].platform
    on_accel = platform != 'cpu'
    # Reference-parity geometry on an accelerator; a small smoke shape on
    # CPU so the bench stays runnable anywhere.
    stack = int(os.environ.get('BENCH_STACK', 16))
    size = int(os.environ.get('BENCH_SIZE', 224 if on_accel else 64))
    # batch sweep on v5e (lanes lookup): 8 → 26.9, 16 → 28.4, 32 → 28.8
    # clips/s; 16 takes nearly all of the win at half the HBM footprint
    batch = int(os.environ.get('BENCH_BATCH', 16 if on_accel else 1))
    iters = int(os.environ.get('BENCH_ITERS', 8 if on_accel else 2))

    device = jax_device(platform)
    params = jax.device_put({
        'rgb': transplant(i3d_model.init_state_dict(modality='rgb')),
        'flow': transplant(i3d_model.init_state_dict(modality='flow')),
        'raft': transplant(raft_model.init_state_dict()),
    }, device)
    rng = np.random.RandomState(0)
    all_stacks = jax.device_put(
        rng.randint(0, 255, size=(iters, batch, stack + 1, size, size, 3))
        .astype(np.float32), device)

    kwargs = dict(pads=(0, 0, 0, 0), streams=('rgb', 'flow'),
                  crop_size=min(224, size))

    def chained(p, xs):
        # per-stream checksums double as the finiteness guard (any NaN/Inf
        # element propagates into its stream's sum) without compiling a
        # second full-graph executable
        def body(acc, stacks):
            o = fused_two_stream_step(p, stacks, **kwargs)
            return {k: acc[k] + o[k].sum() for k in acc}, None
        acc, _ = lax.scan(
            body, {k: jnp.float32(0) for k in kwargs['streams']}, xs)
        return acc

    jitted = jax.jit(chained)
    warm = jax.tree_util.tree_map(float, jitted(params, all_stacks))
    for s, v in warm.items():                      # compile + warmup + guard
        assert np.isfinite(v), f'{s} checksum not finite'

    t0 = time.perf_counter()
    checksum = jax.tree_util.tree_map(float, jitted(params, all_stacks))
    elapsed = time.perf_counter() - t0             # value fetch = real time
    assert all(np.isfinite(v) for v in checksum.values()), checksum

    clips_per_sec = batch * iters / elapsed
    print(json.dumps({
        'metric': f'i3d_two_stream_clips_per_sec_{platform}'
                  f'_stack{stack}_{size}px',
        'value': round(clips_per_sec, 3),
        'unit': 'clips/sec/chip',
        'vs_baseline': round(clips_per_sec / BASELINE_CLIPS_PER_SEC, 3),
    }))


if __name__ == '__main__':
    main()
