#!/usr/bin/env python3
"""Measure numerics parity vs the reference implementation; write PARITY.md.

For every model family this runs the reference-side computation (the
reference repo's own torch nets where importable, state-dict-compatible
torch mirrors where the reference delegates to torchvision/timm) and ours
on identical inputs and weights, then the end-to-end pipelines on a real
clip, and reports feature rel L2 against the ≤1e-3 bar (BASELINE.json).

Weights: seeded-random by default (the reference's pretrained blobs are
absent in this environment — reference/.MISSING_LARGE_BLOBS). Pass
``--checkpoints DIR`` holding files provisioned by tools/fetch_checkpoints
(i3d_rgb.pt, i3d_flow.pt, raft-sintel.pth, S3D_kinetics400_torchified.pt)
to measure the same numbers on real weights — the loaders put them into
BOTH sides, so the comparison methodology is identical.

    python tools/measure_parity.py --out PARITY.md          # full (~30 min CPU)
    python tools/measure_parity.py --only e2e_i3d --json    # one row
"""
from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

import numpy as np

REPO = Path(__file__).resolve().parents[1]
REFERENCE = Path('/root/reference')
sys.path.insert(0, str(REPO))

BAR = 1e-3


def _rel(a, b):
    return float(np.linalg.norm(np.asarray(a) - np.asarray(b))
                 / max(np.linalg.norm(np.asarray(b)), 1e-12))


def _load_sd(ckpt_dir, *names):
    """First existing checkpoint under --checkpoints, else None (seeded)."""
    import torch
    if ckpt_dir is None:
        return None
    for name in names:
        p = Path(ckpt_dir) / name
        if p.exists():
            sd = torch.load(str(p), map_location='cpu', weights_only=False)
            if isinstance(sd, dict) and 'state_dict' in sd:
                sd = sd['state_dict']
            return sd
    return None


def _highest():
    import jax
    jax.config.update('jax_platforms', 'cpu')
    return jax.default_matmul_precision('highest')


# -- model-level measurements ------------------------------------------------

def measure_i3d(ckpt_dir):
    import torch

    from models.i3d.i3d_src.i3d_net import I3D
    from video_features_tpu.models import i3d as i3d_model
    from video_features_tpu.transplant.torch2jax import (
        strip_dataparallel, transplant,
    )
    rows = []
    for modality, ch, ckpts in [
            ('rgb', 3, ('i3d_rgb.pt',)), ('flow', 2, ('i3d_flow.pt',))]:
        torch.manual_seed(0)
        net = I3D(num_classes=400, modality=modality).eval()
        sd = _load_sd(ckpt_dir, *ckpts)
        real = sd is not None
        if real:
            net.load_state_dict(strip_dataparallel(sd))
        params = transplant(net.state_dict())
        x = (np.random.RandomState(0).rand(1, 16, 224, 224, ch)
             .astype(np.float32) * 2 - 1)
        with torch.no_grad():
            ref = net(torch.from_numpy(x).permute(0, 4, 1, 2, 3),
                      features=True).numpy()
        with _highest():
            ours = np.asarray(i3d_model.forward(params, x, features=True))
        rows.append((f'i3d {modality} tower', _rel(ours, ref), real))
    return rows


def measure_raft(ckpt_dir):
    import torch

    from models.raft.raft_src.raft import RAFT
    from video_features_tpu.models import raft as raft_model
    from video_features_tpu.transplant.torch2jax import (
        strip_dataparallel, transplant,
    )
    torch.manual_seed(0)
    net = RAFT().eval()
    sd = _load_sd(ckpt_dir, 'raft-sintel.pth')
    real = sd is not None
    if real:
        net.load_state_dict(strip_dataparallel(sd))
    params = transplant(net.state_dict())
    rng = np.random.RandomState(0)
    f1 = (rng.rand(1, 128, 160, 3) * 255).astype(np.float32)
    f2 = np.clip(f1 + rng.rand(1, 128, 160, 3) * 20, 0, 255).astype(np.float32)
    with torch.no_grad():
        ref = net(torch.from_numpy(f1).permute(0, 3, 1, 2),
                  torch.from_numpy(f2).permute(0, 3, 1, 2)
                  ).permute(0, 2, 3, 1).numpy()
    with _highest():
        ours = np.asarray(raft_model.forward(params, f1, f2))
    return [('raft flow (20 GRU iters)', _rel(ours, ref), real)]


def measure_s3d(ckpt_dir):
    import torch

    from models.s3d.s3d_src.s3d import S3D
    from video_features_tpu.models import s3d as s3d_model
    from video_features_tpu.transplant.torch2jax import transplant
    torch.manual_seed(0)
    net = S3D(num_class=400).eval()
    sd = _load_sd(ckpt_dir, 'S3D_kinetics400_torchified.pt')
    real = sd is not None
    if real:
        net.load_state_dict(sd)
    params = transplant(net.state_dict())
    x = np.random.RandomState(0).rand(1, 32, 224, 224, 3).astype(np.float32)
    with torch.no_grad():
        ref = net(torch.from_numpy(x).permute(0, 4, 1, 2, 3),
                  features=True).numpy()
    with _highest():
        ours = np.asarray(s3d_model.forward(params, x, features=True))
    return [('s3d features', _rel(ours, ref), real)]


def measure_clip(ckpt_dir):
    import importlib.util

    import torch

    from video_features_tpu.models import clip as clip_model
    from video_features_tpu.transplant.torch2jax import transplant
    spec = importlib.util.spec_from_file_location(
        'ref_clip_model', REFERENCE / 'models/clip/clip_src/model.py')
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    torch.manual_seed(0)
    net = mod.CLIP(embed_dim=512, image_resolution=224, vision_layers=12,
                   vision_width=768, vision_patch_size=32, context_length=77,
                   vocab_size=512, transformer_width=512, transformer_heads=8,
                   transformer_layers=2).eval().float()
    params = transplant(net.state_dict(),
                        no_transpose=set(clip_model.NO_TRANSPOSE))
    x = np.random.RandomState(0).rand(2, 224, 224, 3).astype(np.float32)
    with torch.no_grad():
        ref = net.encode_image(torch.from_numpy(x).permute(0, 3, 1, 2)).numpy()
    with _highest():
        ours = np.asarray(clip_model.encode_image(params, x, 'ViT-B/32'))
    return [('clip image tower (ViT-B/32 geometry)', _rel(ours, ref), False)]


def measure_vggish(ckpt_dir):
    from models.vggish.vggish_src import mel_features as ref_mel

    from video_features_tpu.ops import audio as audio_ops
    rng = np.random.RandomState(0)
    data = rng.randn(16000 * 2).astype(np.float64) * 0.1
    ours = audio_ops.log_mel_spectrogram(data, 16000)
    theirs = ref_mel.log_mel_spectrogram(
        data, audio_sample_rate=16000, log_offset=0.01,
        window_length_secs=0.025, hop_length_secs=0.010,
        num_mel_bins=64, lower_edge_hertz=125.0, upper_edge_hertz=7500.0)
    return [('vggish log-mel frontend', _rel(ours, theirs), 'n/a')]


def measure_mirrors(ckpt_dir):
    import torch

    from tests.torch_mirrors import (
        TorchConvNeXt, TorchResNet, TorchVideoResNet, randomize_bn_stats,
    )
    from video_features_tpu.models import convnext as convnext_model
    from video_features_tpu.models import r21d as r21d_model
    from video_features_tpu.models import resnet as resnet_model
    from video_features_tpu.transplant.torch2jax import transplant
    rows = []
    rng = np.random.RandomState(1)

    torch.manual_seed(0)
    m = TorchResNet('resnet50').eval()
    randomize_bn_stats(m)
    sd = _load_sd(ckpt_dir, 'resnet50-0676ba61.pth')
    real = sd is not None
    if real:
        m.load_state_dict(sd)
    x = rng.rand(2, 112, 112, 3).astype(np.float32) * 2 - 1
    with torch.no_grad():
        ref = m(torch.from_numpy(x).permute(0, 3, 1, 2)).numpy()
    with _highest():
        ours = np.asarray(resnet_model.forward(
            transplant(m.state_dict()), x, arch='resnet50'))
    rows.append(('resnet50 (torchvision mirror)', _rel(ours, ref), real))

    torch.manual_seed(0)
    m = TorchVideoResNet('r2plus1d_18').eval()
    randomize_bn_stats(m)
    sd = _load_sd(ckpt_dir, 'r2plus1d_18-91a641e6.pth')
    real = sd is not None
    if real:
        m.load_state_dict(sd)
    x = rng.rand(2, 8, 56, 56, 3).astype(np.float32) * 2 - 1
    with torch.no_grad():
        ref = m(torch.from_numpy(x).permute(0, 4, 1, 2, 3)).numpy()
    with _highest():
        ours = np.asarray(r21d_model.forward(transplant(m.state_dict()), x,
                                             arch='r2plus1d_18'))
    rows.append(('r2plus1d_18 (torchvision mirror)', _rel(ours, ref), real))

    # random-weight mirror rows, one per native timm-layout family:
    # (label, mirror class, mirror kwargs, model module, arch, input px).
    # Each runs seed → randomize BN stats (no-op for LN-only nets) →
    # torch forward → transplant → ours, identically.
    from tests.torch_mirrors import (
        TorchBeit, TorchEfficientNet, TorchMixer, TorchMobileNetV3,
        TorchRegNet, TorchSwin,
    )
    from video_features_tpu.models import beit as beit_model
    from video_features_tpu.models import efficientnet as eff_model
    from video_features_tpu.models import mixer as mixer_model
    from video_features_tpu.models import mobilenetv3 as mnv3_model
    from video_features_tpu.models import regnet as regnet_model
    from video_features_tpu.models import swin as swin_model
    mirror_specs = [
        ('convnext_tiny (timm mirror)',
         TorchConvNeXt, {}, convnext_model, 'convnext_tiny', 96),
        # 192px: stage-2 runs the real shifted-window mask, stage-3 maps
        # are smaller than the window (the window-collapse rule)
        ('swin_tiny (timm mirror, shifted windows)',
         TorchSwin, dict(img_size=192), swin_model,
         'swin_tiny_patch4_window7_224', 192),
        ('resnext50_32x4d (torchvision mirror, grouped)',
         TorchResNet, {}, resnet_model, 'resnext50_32x4d', 112),
        ('efficientnet_b0 (timm mirror, dw/SE)',
         TorchEfficientNet, {}, eff_model, 'efficientnet_b0', 128),
        ('regnety_008 (timm mirror, grouped+SE)',
         TorchRegNet, {}, regnet_model, 'regnety_008', 128),
        ('mobilenetv3_large_100 (timm mirror, h-swish/h-sig SE)',
         TorchMobileNetV3, {}, mnv3_model, 'mobilenetv3_large_100', 128),
        # full 224: the rel-pos window (14²) is resolution-tied
        ('beit_base (timm mirror, rel-pos bias + layer scale)',
         TorchBeit, {}, beit_model, 'beit_base_patch16_224', 224),
        # full 224: the token-mix MLP width (196) is resolution-tied
        ('mixer_b16 (timm mirror, token-mixing MLP)',
         TorchMixer, {}, mixer_model, 'mixer_b16_224', 224),
    ]
    for label, mirror_cls, kwargs, module, arch, px in mirror_specs:
        torch.manual_seed(0)
        m = mirror_cls(arch, **kwargs).eval()
        randomize_bn_stats(m)
        x = rng.rand(2, px, px, 3).astype(np.float32) * 2 - 1
        with torch.no_grad():
            ref = m(torch.from_numpy(x).permute(0, 3, 1, 2)).numpy()
        with _highest():
            ours = np.asarray(module.forward(
                transplant(m.state_dict()), x, arch=arch))
        rows.append((label, _rel(ours, ref), False))
    return rows


# -- end-to-end measurements -------------------------------------------------

def _make_clip33(tmp):
    import cv2
    src = REFERENCE / 'sample' / 'v_ZNVhz7ctTq0.mp4'
    out = str(Path(tmp) / 'clip33.mp4')
    cap = cv2.VideoCapture(str(src))
    wr = cv2.VideoWriter(out, cv2.VideoWriter_fourcc(*'mp4v'),
                         cap.get(cv2.CAP_PROP_FPS),
                         (int(cap.get(3)), int(cap.get(4))))
    for _ in range(33):
        ok, f = cap.read()
        if not ok:
            break
        wr.write(f)
    wr.release()
    cap.release()
    return out


def measure_e2e_i3d(ckpt_dir):
    import tempfile

    import torch

    from tests.reference_pipeline import (
        build_reference_nets, run_reference_i3d, save_state_dicts,
    )
    from video_features_tpu.config import load_config
    from video_features_tpu.registry import create_extractor
    from video_features_tpu.transplant.torch2jax import strip_dataparallel
    with tempfile.TemporaryDirectory() as tmp:
        video = _make_clip33(tmp)
        nets = build_reference_nets(seed=0)
        real = False
        for key, names in [('rgb', ('i3d_rgb.pt',)),
                           ('flow', ('i3d_flow.pt',)),
                           ('raft', ('raft-sintel.pth',))]:
            sd = _load_sd(ckpt_dir, *names)
            if sd is not None:
                nets[key].load_state_dict(strip_dataparallel(sd))
                real = True
        ckpts = save_state_dicts(nets, Path(tmp) / 'ckpts')
        golden = run_reference_i3d(video, nets, stack_size=16)
        args = load_config('i3d', overrides={
            'video_paths': video, 'device': 'cpu', 'precision': 'highest',
            'decode_backend': 'cv2', 'stack_size': 16, 'step_size': 16,
            'concat_rgb_flow': True,
            'i3d_rgb_checkpoint_path': ckpts['rgb'],
            'i3d_flow_checkpoint_path': ckpts['flow'],
            'raft_checkpoint_path': ckpts['raft'],
            'output_path': str(Path(tmp) / 'o'),
            'tmp_path': str(Path(tmp) / 't')})
        out = create_extractor(args).extract(video)
        rows = [
            ('E2E i3d rgb stream (file→features)',
             _rel(out['rgb'], golden['rgb']), real),
            ('E2E i3d flow stream (file→features)',
             _rel(out['flow'], golden['flow']), real),
            ('E2E i3d rgb∥flow concat (T, 2048)',
             _rel(np.concatenate([out['rgb'], out['flow']], -1),
                  np.concatenate([golden['rgb'], golden['flow']], -1)),
             real),
        ]
        # Same golden, decoded with the native C++ backend on our side
        # (reference side stays cv2 — its own decoder). Since round 5 the
        # native backend reproduces cv2's yuv420p→RGB integer tables
        # bit-exactly (native/yuv2rgb_cv2_tables.h, fitted by
        # tools/fit_cv2_yuv_tables.py), so this row must equal the cv2
        # row EXACTLY — it pins decode-backend equivalence at the feature
        # level, which is what let decode_backend default to 'auto'.
        from video_features_tpu.io import native
        if native.available():
            args_native = load_config('i3d', overrides={
                **{k: args[k] for k in (
                    'video_paths', 'device', 'precision', 'stack_size',
                    'step_size', 'concat_rgb_flow',
                    'i3d_rgb_checkpoint_path', 'i3d_flow_checkpoint_path',
                    'raft_checkpoint_path')},
                'decode_backend': 'native',
                'output_path': str(Path(tmp) / 'on'),
                'tmp_path': str(Path(tmp) / 'tn')})
            out_n = create_extractor(args_native).extract(video)
            rows.append(
                ('E2E i3d concat, NATIVE decode (ours) vs cv2 (ref)',
                 _rel(np.concatenate([out_n['rgb'], out_n['flow']], -1),
                      np.concatenate([golden['rgb'], golden['flow']], -1)),
                 real))
        return rows


def measure_e2e_r21d(ckpt_dir):
    import tempfile

    import torch

    from tests.reference_pipeline import (
        R21D_OVERRIDES, build_reference_r21d_net, run_reference_r21d,
    )
    from video_features_tpu.config import load_config
    from video_features_tpu.registry import create_extractor
    with tempfile.TemporaryDirectory() as tmp:
        video = _make_clip33(tmp)
        sd = _load_sd(ckpt_dir, 'r2plus1d_18-91a641e6.pth')
        real = sd is not None
        net = build_reference_r21d_net(seed=0, state_dict=sd)
        ckpt = Path(tmp) / 'r21d.pt'
        torch.save(net.state_dict(), str(ckpt))
        ref = run_reference_r21d(video, net, stack_size=16, step_size=16)
        args = load_config('r21d', overrides={
            **R21D_OVERRIDES, 'video_paths': video,
            'checkpoint_path': str(ckpt),
            'output_path': str(Path(tmp) / 'o'),
            'tmp_path': str(Path(tmp) / 't')})
        ours = create_extractor(args).extract(video)['r21d']
        return [('E2E r21d (T, 512) (file→features)', _rel(ours, ref), real)]


def measure_e2e_clip(ckpt_dir):
    import tempfile

    import torch

    from tests.reference_pipeline import build_reference_clip, run_reference_clip
    from video_features_tpu.config import load_config
    from video_features_tpu.registry import create_extractor
    with tempfile.TemporaryDirectory() as tmp:
        video = _make_clip33(tmp)
        net = build_reference_clip(seed=0)
        ckpt = Path(tmp) / 'clip.pt'
        torch.save(net.state_dict(), str(ckpt))
        ref = run_reference_clip(video, net)
        args = load_config('clip', overrides={
            'video_paths': video, 'device': 'cpu', 'precision': 'highest',
            'decode_backend': 'cv2', 'batch_size': 16,
            'model_name': 'custom', 'checkpoint_path': str(ckpt),
            'output_path': str(Path(tmp) / 'o'),
            'tmp_path': str(Path(tmp) / 't')})
        ours = create_extractor(args).extract(video)['clip']
        return [('E2E clip (T, 512) (file→features)', _rel(ours, ref),
                 False)]


def measure_e2e_s3d(ckpt_dir):
    import tempfile

    import torch

    from models.s3d.s3d_src.s3d import S3D
    from tests.reference_pipeline import run_reference_s3d
    from video_features_tpu.config import load_config
    from video_features_tpu.registry import create_extractor
    with tempfile.TemporaryDirectory() as tmp:
        video = _make_clip33(tmp)
        torch.manual_seed(0)
        net = S3D(num_class=400).eval()
        sd = _load_sd(ckpt_dir, 'S3D_kinetics400_torchified.pt')
        real = sd is not None
        if real:
            net.load_state_dict(sd)
        ckpt = Path(tmp) / 's3d.pt'
        torch.save(net.state_dict(), str(ckpt))
        ref = run_reference_s3d(video, net, stack_size=16, step_size=16)
        args = load_config('s3d', overrides={
            'video_paths': video, 'device': 'cpu', 'precision': 'highest',
            'decode_backend': 'cv2', 'stack_size': 16, 'step_size': 16,
            'extraction_fps': None, 'checkpoint_path': str(ckpt),
            'output_path': str(Path(tmp) / 'o'),
            'tmp_path': str(Path(tmp) / 't')})
        ours = create_extractor(args).extract(video)['s3d']
        return [('E2E s3d (T, 1024) (file→features)', _rel(ours, ref), real)]


def measure_e2e_resnet(ckpt_dir):
    import tempfile

    import torch

    from tests.reference_pipeline import run_reference_resnet
    from tests.torch_mirrors import TorchResNet, randomize_bn_stats
    from video_features_tpu.config import load_config
    from video_features_tpu.registry import create_extractor
    with tempfile.TemporaryDirectory() as tmp:
        video = _make_clip33(tmp)
        torch.manual_seed(0)
        net = TorchResNet('resnet50').eval()
        randomize_bn_stats(net)
        sd = _load_sd(ckpt_dir, 'resnet50-0676ba61.pth')
        real = sd is not None
        if real:
            net.load_state_dict(sd)
        ckpt = Path(tmp) / 'resnet50.pt'
        torch.save(net.state_dict(), str(ckpt))
        ref = run_reference_resnet(video, net)
        args = load_config('resnet', overrides={
            'video_paths': video, 'device': 'cpu', 'precision': 'highest',
            'decode_backend': 'cv2', 'batch_size': 16,
            'model_name': 'resnet50', 'checkpoint_path': str(ckpt),
            'output_path': str(Path(tmp) / 'o'),
            'tmp_path': str(Path(tmp) / 't')})
        ours = create_extractor(args).extract(video)['resnet']
        return [('E2E resnet50 (T, 2048) (file→features)', _rel(ours, ref),
                 real)]


def measure_e2e_raft(ckpt_dir):
    import tempfile

    import cv2
    import torch

    from models.raft.raft_src.raft import InputPadder
    from tests.reference_pipeline import build_reference_nets, save_state_dicts
    from video_features_tpu.config import load_config
    from video_features_tpu.registry import create_extractor
    from video_features_tpu.transplant.torch2jax import strip_dataparallel
    with tempfile.TemporaryDirectory() as tmp:
        video = _make_clip33(tmp)
        nets = build_reference_nets(seed=0, streams=('flow',))
        sd = _load_sd(ckpt_dir, 'raft-sintel.pth')
        real = sd is not None
        if real:
            nets['raft'].load_state_dict(strip_dataparallel(sd))
        ckpts = save_state_dicts({'raft': nets['raft']}, Path(tmp) / 'ckpts')
        cap = cv2.VideoCapture(video)
        frames = []
        while True:
            ok, bgr = cap.read()
            if not ok:
                break
            frames.append(cv2.cvtColor(bgr, cv2.COLOR_BGR2RGB))
        cap.release()
        batch = torch.from_numpy(np.stack(frames)).permute(0, 3, 1, 2).float()
        padder = InputPadder(batch.shape)
        with torch.no_grad():
            p = padder.pad(batch)
            ref = torch.cat([padder.unpad(nets['raft'](p[i:i + 1],
                                                       p[i + 1:i + 2]))
                             for i in range(len(frames) - 1)]).numpy()
        args = load_config('raft', overrides={
            'video_paths': video, 'device': 'cpu', 'precision': 'highest',
            'decode_backend': 'cv2', 'batch_size': 16,
            'checkpoint_path': ckpts['raft'],
            'output_path': str(Path(tmp) / 'o'),
            'tmp_path': str(Path(tmp) / 't')})
        ours = create_extractor(args).extract(video)['raft']
        return [('E2E raft flow field (file→flows)', _rel(ours, ref), real)]


def measure_e2e_vggish(ckpt_dir):
    """Whole-file wav→(Ta,128) against the reference's own mel_features +
    framing + the state-dict-matched VGG (tests/reference_pipeline.
    run_reference_vggish; the mp4 leg needs ffmpeg, not present here).
    Two rows: a 16 kHz wav (resample-free) and a 44.1 kHz wav — the rate
    real mp4 audio tracks have — where the reference side resamples via
    the literal resampy-0.4.2 transcription and ours runs the production
    vectorized Kaiser resampler (ops/audio.py:resample_kaiser)."""
    import tempfile

    import torch

    from tests.reference_pipeline import run_reference_vggish
    from tests.torch_mirrors import TorchVGGish
    from video_features_tpu.config import load_config
    from video_features_tpu.registry import create_extractor
    rows = []
    with tempfile.TemporaryDirectory() as tmp:
        from tests.reference_pipeline import write_real_audio_wav

        torch.manual_seed(0)
        net = TorchVGGish().eval()
        sd = _load_sd(ckpt_dir, 'vggish-10086976.pth')
        real = sd is not None
        if real:
            net.load_state_dict(sd)
        ckpt = Path(tmp) / 'vggish.pt'
        torch.save(net.state_dict(), str(ckpt))
        for sr, label in ((16000, 'E2E vggish (Ta, 128) (file→features)'),
                          (44100, 'E2E vggish 44.1 kHz (Kaiser resample)')):
            wav = write_real_audio_wav(str(Path(tmp) / f'audio{sr}.wav'),
                                       sr=sr)
            ref = run_reference_vggish(wav, net)
            args = load_config('vggish', overrides={
                'video_paths': wav, 'device': 'cpu', 'precision': 'highest',
                'checkpoint_path': str(ckpt),
                'output_path': str(Path(tmp) / f'o{sr}'),
                'tmp_path': str(Path(tmp) / f't{sr}')})
            ours = create_extractor(args).extract(wav)['vggish']
            rows.append((label, _rel(ours, ref), real))
    return rows


def measure_e2e_clip_zeroshot(ckpt_dir):
    """Whole zero-shot pipeline (decode → visual tower → real-prompt BPE →
    text tower → temperature cosine logits → softmax) vs the reference's
    own pieces (extract_clip.py:86-105); prompts' token ids are mapped
    into the reduced test vocab identically on both sides."""
    import tempfile

    import jax
    import jax.numpy as jnp
    import torch

    from tests.reference_pipeline import build_reference_clip, run_reference_clip
    from video_features_tpu.config import load_config
    from video_features_tpu.models import clip as clip_model
    from video_features_tpu.registry import create_extractor
    from video_features_tpu.transplant.torch2jax import transplant
    from video_features_tpu.utils.clip_tokenizer import tokenize
    with tempfile.TemporaryDirectory() as tmp:
        video = _make_clip33(tmp)
        net = build_reference_clip(seed=0)
        prompts = [f'a photo of {c}' for c in
                   ('archery', 'bowling', 'dancing', 'juggling balls',
                    'playing guitar', 'surfing water')]
        tokens = np.asarray(tokenize(prompts))
        content = tokens > 0
        eot = tokens == tokens.max(axis=1, keepdims=True)
        mapped = np.where(content, tokens % 509 + 1, 0)
        mapped = np.where(eot, 511, mapped).astype(np.int64)

        ref_vis = run_reference_clip(video, net)
        with torch.no_grad():
            t = net.encode_text(torch.from_numpy(mapped)).double()
            v = torch.from_numpy(ref_vis).double()
            v = v / v.norm(dim=1, keepdim=True)
            t = t / t.norm(dim=1, keepdim=True)
            ref = (net.logit_scale.exp().double()
                   * v @ t.T).softmax(dim=-1).numpy()

        ckpt = Path(tmp) / 'clip.pt'
        torch.save(net.state_dict(), str(ckpt))
        args = load_config('clip', overrides={
            'video_paths': video, 'device': 'cpu', 'precision': 'highest',
            'decode_backend': 'cv2', 'batch_size': 16, 'model_name': 'custom',
            'checkpoint_path': str(ckpt),
            'output_path': str(Path(tmp) / 'o'),
            'tmp_path': str(Path(tmp) / 't')})
        ex = create_extractor(args)
        vis = ex.extract(video)['clip']
        with jax.default_matmul_precision('highest'):
            # ex.params IS the transplanted checkpoint — reuse it for the
            # text tower so both towers come from the extractor's load path
            txt = np.asarray(clip_model.encode_text(ex.params, mapped,
                                                    ex.arch))
            logits = clip_model.zero_shot_logits(
                ex.params, jnp.asarray(vis), jnp.asarray(txt))
        ours = np.asarray(jax.nn.softmax(logits, axis=-1))
        return [('E2E clip zero-shot prob table (file→top-k)',
                 _rel(ours, ref), False)]


def measure_hf_clip(ckpt_dir):
    """CLIP ViT-B/32 at FULL geometry vs transformers.CLIPModel — an
    independent cross-implementation check (HF's CLIP is code we didn't
    write), through the production converter
    (transplant/hf.py:clip_to_openai). Replaces the reduced-geometry
    caveat on the reference-side clip rows. Harness shared with
    tests/test_hf_crosscheck.py (tests/clip_crosscheck.py)."""
    from tests.clip_crosscheck import run_clip_vitb32_crosscheck

    r = run_clip_vitb32_crosscheck()
    return [
        ('clip ViT-B/32 FULL image tower (vs transformers)',
         _rel(r['got_img'], r['ref_img']), False),
        ('clip ViT-B/32 FULL text tower (vs transformers)',
         _rel(r['got_txt'], r['ref_txt']), False),
        ('clip ViT-B/32 FULL zero-shot logits (vs transformers)',
         _rel(r['got_logits'], r['ref_logits']), False),
    ]


MEASURES = {
    'i3d': measure_i3d,
    'raft': measure_raft,
    's3d': measure_s3d,
    'clip': measure_clip,
    'vggish': measure_vggish,
    'hf_clip': measure_hf_clip,
    'mirrors': measure_mirrors,
    'e2e_i3d': measure_e2e_i3d,
    'e2e_clip': measure_e2e_clip,
    'e2e_r21d': measure_e2e_r21d,
    'e2e_s3d': measure_e2e_s3d,
    'e2e_resnet': measure_e2e_resnet,
    'e2e_raft': measure_e2e_raft,
    'e2e_vggish': measure_e2e_vggish,
    'e2e_clip_zeroshot': measure_e2e_clip_zeroshot,
}


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument('--out', default=None, help='write PARITY.md here')
    ap.add_argument('--only', nargs='*', default=None,
                    help=f'subset of: {", ".join(MEASURES)}')
    ap.add_argument('--checkpoints', default=None,
                    help='dir of real checkpoints (fetch_checkpoints.py)')
    ap.add_argument('--json', action='store_true')
    ns = ap.parse_args()

    if str(REFERENCE) not in sys.path:
        # APPEND, never prepend: the reference's `tests` is a regular
        # package and would shadow our tests.* helper modules if it came
        # before REPO on sys.path (repo tests/__init__.py documents this)
        sys.path.append(str(REFERENCE))

    rows = []
    for name in (ns.only or MEASURES):
        t0 = time.time()
        try:
            new = list(MEASURES[name](ns.checkpoints))
        except Exception as e:
            new = [(f'{name} [FAILED: {type(e).__name__}: {e}]',
                    float('nan'), False)]
        print(f'# {name}: {time.time() - t0:.0f}s', file=sys.stderr)
        rows.extend(new)
        if ns.json:
            for r, rel, real in new:
                print(json.dumps({
                    'measure': r,
                    'rel_l2': rel if rel == rel else None,  # NaN → null
                    'real_weights': real}))

    lines = []
    for r, rel, real in rows:
        mark = '✅' if rel == rel and rel < BAR else '⚠️'
        w = ('weight-free (DSP)' if real == 'n/a'
             else 'real' if real else 'seeded-random')
        lines.append(f'| {r} | {rel:.2e} | {w} | {mark} |')
        if not ns.json:
            print(lines[-1])
    if ns.out:
        header = Path(REPO / 'tools' / 'parity_header.md')
        text = (header.read_text() if header.exists() else
                '# PARITY — measured numerics vs the reference\n\n')
        text += ('| measurement | rel L2 | weights | ≤1e-3 |\n'
                 '|---|---|---|---|\n' + '\n'.join(lines) + '\n')
        Path(ns.out).write_text(text)
        print(f'wrote {ns.out}', file=sys.stderr)
    return 0


if __name__ == '__main__':
    raise SystemExit(main())
