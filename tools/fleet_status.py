#!/usr/bin/env python3
"""One-look fleet health: the router's per-backend table as text.

Connects to a fleet router's loopback port (``fleet`` command), asks for
its metrics document, and prints one row per configured backend —
health, PROBE FRESHNESS (``age_s``: seconds since the last probe, so a
stale last-good row is distinguishable from a live healthy backend),
drain state, live queue depth, cache hit rate, and warm-pool build
counters — plus the router's own routing/failover counters. The same
document backs the router's HTTP ``GET /v1/metrics``; this tool is the
no-auth operator surface for the loopback deployment shape.

Usage:
    python tools/fleet_status.py [--host 127.0.0.1] --port 9310 [--json]

Exit codes (monitorable — cron/CI can alert on them):
    0  every configured backend is healthy and not draining
    1  degraded — at least one backend is unhealthy or draining, but
       the fleet still has an eligible backend
    2  down — no eligible backend at all, or the router itself is
       unreachable
"""
from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))


def _fmt(value, width: int, suffix: str = '') -> str:
    if value is None:
        return '-'.rjust(width)
    if isinstance(value, float):
        return f'{value:.2f}{suffix}'.rjust(width)
    return f'{value}{suffix}'.rjust(width)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument('--host', default='127.0.0.1',
                    help='the router host (default: loopback)')
    ap.add_argument('--port', type=int, required=True,
                    help='the router loopback port (fleet_port)')
    ap.add_argument('--timeout-s', type=float, default=5.0,
                    help='connect deadline for reaching the router')
    ap.add_argument('--json', action='store_true',
                    help='print the raw fleet metrics document instead '
                         'of the table')
    ns = ap.parse_args(argv)

    from video_features_tpu.serve.client import ServeClient, ServeError
    try:
        doc = ServeClient(ns.port, host=ns.host,
                          connect_timeout_s=ns.timeout_s).metrics()
    except (ServeError, OSError) as e:
        print(f'error: router at {ns.host}:{ns.port} unreachable: {e}',
              file=sys.stderr)
        return 2
    fleet = doc.get('fleet')
    if not isinstance(fleet, dict):
        print(f'error: {ns.host}:{ns.port} answered metrics without a '
              f'fleet section — is that a serve daemon, not a router?',
              file=sys.stderr)
        return 2

    if ns.json:
        print(json.dumps(fleet, sort_keys=True))
    else:
        routed = fleet.get('routed') or {}
        print(f"fleet router {ns.host}:{ns.port}  "
              f"uptime={fleet.get('uptime_s')}s  "
              f"draining={fleet.get('draining')}  "
              f"failovers={fleet.get('failovers')}  "
              f"rejected={fleet.get('rejected')}")
        header = (f"{'backend':24} {'health':>9} {'age_s':>6} "
                  f"{'drain':>5} {'queue':>5} {'hit%':>6} "
                  f"{'compiled':>8} {'loaded':>6} {'routed':>7}  "
                  f"last_error")
        print(header)
        for addr, row in sorted((fleet.get('backends') or {}).items()):
            hit = row.get('cache_hit_rate')
            print(f"{addr:24} "
                  f"{'healthy' if row.get('healthy') else 'DOWN':>9} "
                  f"{_fmt(row.get('probe_age_s'), 6)} "
                  f"{'yes' if row.get('draining') else 'no':>5} "
                  f"{_fmt(row.get('queue_depth'), 5)} "
                  f"{_fmt(None if hit is None else 100 * hit, 6)} "
                  f"{_fmt(row.get('builds_compiled'), 8)} "
                  f"{_fmt(row.get('builds_loaded'), 6)} "
                  f"{_fmt(routed.get(addr), 7)}  "
                  f"{row.get('last_error') or ''}")

    backends = fleet.get('backends') or {}
    eligible = fleet.get('eligible') or []
    if not eligible:
        return 2
    degraded = any(not row.get('healthy') or row.get('draining')
                   for row in backends.values())
    return 1 if degraded else 0


if __name__ == '__main__':
    raise SystemExit(main())
