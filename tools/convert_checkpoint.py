#!/usr/bin/env python3
"""Convert a PyTorch checkpoint to a torch-free .npz the extractors load.

Run once on any machine with torch installed; the output .npz contains the
fully transplanted JAX pytree (layout transposes, DataParallel-prefix
stripping, fp16 upcast all already applied), so production TPU hosts need
no torch:

    python tools/convert_checkpoint.py raft-sintel.pth raft-sintel.npz
    python -m video_features_tpu feature_type=raft \
        checkpoint_path=raft-sintel.npz ...

``--key`` selects a sub-dict for wrapped checkpoints; ``--no-transpose``
names 2-D weights that must keep torch layout (embedding tables).

``--hf-family {vit,deit,beit,convnext,swin,regnet} --arch <timm-name>`` converts a
HuggingFace `transformers` checkpoint instead: the HF state dict is
re-keyed into the timm layout (transplant/hf.py) before the transplant —
a weights-provisioning path for the native timm families that needs no
pip-timm:

    python tools/convert_checkpoint.py pytorch_model.bin swin_tiny.npz \
        --hf-family swin --arch swin_tiny_patch4_window7_224

``--hf-family clip`` re-keys a transformers CLIPModel checkpoint into the
OpenAI layout (both towers + logit_scale) and applies CLIP's embedding-
table transpose exemptions automatically; no --arch needed (geometry is
read off the keys, and extract/clip.py re-infers it at load):

    python tools/convert_checkpoint.py clip_pytorch_model.bin vitb32.npz \
        --hf-family clip
"""
from __future__ import annotations

import argparse
import sys
from pathlib import Path

# runnable as a repo script without installation: python tools/convert_checkpoint.py
sys.path.insert(0, str(Path(__file__).resolve().parents[1]))


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument('src', help='input .pt/.pth torch checkpoint')
    ap.add_argument('dst', help='output .npz path')
    ap.add_argument('--key', default=None,
                    help="sub-dict key (e.g. 'state_dict') for wrapped ckpts")
    ap.add_argument('--no-transpose', nargs='*', default=None,
                    help='weight names to keep in torch layout')
    ap.add_argument('--hf-family', default=None,
                    help='re-key a transformers checkpoint for this native '
                         'family (vit/deit/beit/convnext/swin/regnet) before '
                         'transplanting; requires --arch')
    ap.add_argument('--arch', default=None,
                    help='timm arch name the checkpoint targets '
                         '(with --hf-family)')
    ns = ap.parse_args()

    from video_features_tpu.transplant.torch2jax import (
        _flatten, load_torch_checkpoint, save_transplanted, transplant,
    )

    if ns.hf_family:
        if not ns.arch and ns.hf_family != 'clip':
            raise SystemExit('--hf-family requires --arch (the timm name '
                             'whose layout to produce)')
        import torch
        raw = torch.load(ns.src, map_location='cpu', weights_only=True)
        if ns.key:
            raw = raw[ns.key]
        import numpy as np
        no_t = set(ns.no_transpose) if ns.no_transpose else set()
        if ns.hf_family == 'clip':
            from video_features_tpu.models.clip import NO_TRANSPOSE
            from video_features_tpu.transplant.hf import clip_to_openai
            rekeyed = clip_to_openai(raw)
            no_t |= set(NO_TRANSPOSE)
        else:
            from video_features_tpu.transplant.hf import hf_to_timm
            rekeyed = hf_to_timm(ns.hf_family, raw, ns.arch)
        params = transplant(rekeyed, dtype=np.float32,
                            no_transpose=no_t or None)
    else:
        params = load_torch_checkpoint(
            ns.src, key=ns.key,
            no_transpose=set(ns.no_transpose) if ns.no_transpose else None)
    flat = _flatten(params)
    if not flat:
        raise SystemExit(f'no arrays found in {ns.src} (wrong --key?)')
    save_transplanted(params, ns.dst)

    arrays = list(flat.values())
    print(f'wrote {ns.dst}: {len(arrays)} arrays, '
          f'{sum(a.nbytes for a in arrays) / 1e6:.1f} MB '
          f'(dtype {arrays[0].dtype})')
    return 0


if __name__ == '__main__':
    raise SystemExit(main())
