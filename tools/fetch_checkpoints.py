#!/usr/bin/env python3
"""Provision pretrained weights from the sources the reference uses.

One command turns an empty host into one that can run real-weight
extraction — download, sha256-verify, and (by default) convert each
checkpoint to a torch-free ``.npz`` for TPU hosts:

    python tools/fetch_checkpoints.py clip resnet r21d vggish --out ./checkpoints
    python tools/fetch_checkpoints.py all --from-checkout ~/video_features

Sources mirror the reference implementation exactly:
  * clip   — OpenAI's sha256-prefixed URLs (reference
             models/clip/clip_src/clip.py:32-43; the hash embedded in the
             URL path verifies the download);
  * resnet — torchvision IMAGENET1K_V1 weight URLs (reference
             models/resnet/extract_resnet.py:38-40; torch-hub filename
             convention: the trailing ``-xxxxxxxx`` is the sha256 prefix);
  * r21d   — torchvision ``r2plus1d_18`` URL + the ig65m variants via
             ``torch.hub.load('moabitcoin/ig65m-pytorch', ...)`` exactly as
             the reference does (models/r21d/extract_r21d.py:109-118);
  * vggish — the torchvggish release URLs (reference
             models/vggish/vggish_src/vggish_slim.py:119-131);
  * i3d / raft / s3d — the reference BUNDLES these blobs in its repo
             (models/i3d/checkpoints/*.pt, models/raft/checkpoints/*.pth,
             models/s3d/checkpoint/*.pt); they have no public URL, so they
             are copied out of an existing checkout via ``--from-checkout``.

Offline hosts: ``--url-base`` rewrites every URL's origin to a local mirror
(``file:///...`` works), and already-present files that pass their sha256
check are never re-downloaded.
"""
from __future__ import annotations

import argparse
import hashlib
import shutil
import sys
import urllib.request
from pathlib import Path
from typing import Dict, List, Optional

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

_CLIP = 'https://openaipublic.azureedge.net/clip/models'
_TV = 'https://download.pytorch.org/models'
_VGGISH = 'https://github.com/harritaylor/torchvggish/releases/download/v0.1'

# Every artifact: how to obtain it + how to verify it + how to convert it.
#   kind='url'      — download; sha256 full hash, or 'filename' = torch-hub
#                     trailing-8-hex-prefix convention;
#   kind='hub'      — torch.hub.load(repo, model) state_dict (needs network
#                     + torch, like the reference's own path);
#   kind='bundled'  — copy from --from-checkout <reference checkout>.
# 'convert' names the .npz conversion recipe ('plain' | 'clip_jit').
SOURCES: Dict[str, List[dict]] = {
    'clip': [
        {'kind': 'url', 'name': f, 'convert': 'clip_jit',
         'url': f'{_CLIP}/{sha}/{f}', 'sha256': sha}
        for f, sha in [
            ('RN50.pt', 'afeb0e10f9e5a86da6080e35cf09123aca3b358a0c3e3b6c78a7b63bc04b6762'),
            ('RN101.pt', '8fa8567bab74a42d41c5915025a8e4538c3bdbe8804a470a72f30b0d94fab599'),
            ('RN50x4.pt', '7e526bd135e493cef0776de27d5f42653e6b4c8bf9e0f653bb11773263205fdd'),
            ('RN50x16.pt', '52378b407f34354e150460fe41077663dd5b39c54cd0bfd2b27167a4a06ec9aa'),
            ('RN50x64.pt', 'be1cfb55d75a9666199fb2206c106743da0f6468c9d327f3e0d0a543a9919d9c'),
            ('ViT-B-32.pt', '40d365715913c9da98579312b702a82c18be219cc2a73407c4526f58eba950af'),
            ('ViT-B-16.pt', '5806e77cd80f8b59890b7e101eabd078d9fb84e6937f9e85e4ecb61988df416f'),
            ('ViT-L-14.pt', 'b8cca3fd41ae0c99ba7e8951adf17d267cdb84cd88be6f7c2e0eca1737a03836'),
            ('ViT-L-14-336px.pt', '3035c92b350959924f9f00213499208652fc7ea050643e8b385c2dac08641f02'),
        ]
    ],
    'resnet': [
        {'kind': 'url', 'name': f, 'convert': 'plain',
         'url': f'{_TV}/{f}', 'sha256': 'filename'}
        for f in ['resnet18-f37072fd.pth', 'resnet34-b627a593.pth',
                  'resnet50-0676ba61.pth', 'resnet101-63fe2227.pth',
                  'resnet152-394f9c45.pth']
    ],
    'r21d': [
        {'kind': 'url', 'name': 'r2plus1d_18-91a641e6.pth', 'convert': 'plain',
         'url': f'{_TV}/r2plus1d_18-91a641e6.pth', 'sha256': 'filename'},
        {'kind': 'hub', 'name': 'r2plus1d_34_8_ig65m_ft_kinetics.pth',
         'convert': 'plain', 'repo': 'moabitcoin/ig65m-pytorch',
         'model': 'r2plus1d_34_8_kinetics', 'num_classes': 400},
        {'kind': 'hub', 'name': 'r2plus1d_34_32_ig65m_ft_kinetics.pth',
         'convert': 'plain', 'repo': 'moabitcoin/ig65m-pytorch',
         'model': 'r2plus1d_34_32_kinetics', 'num_classes': 400},
    ],
    'vggish': [
        {'kind': 'url', 'name': 'vggish-10086976.pth', 'convert': 'plain',
         'url': f'{_VGGISH}/vggish-10086976.pth', 'sha256': 'filename'},
        {'kind': 'url', 'name': 'vggish_pca_params-970ea276.pth',
         'convert': 'pca',
         'url': f'{_VGGISH}/vggish_pca_params-970ea276.pth',
         'sha256': 'filename'},
    ],
    'i3d': [
        {'kind': 'bundled', 'name': 'i3d_rgb.pt', 'convert': 'plain',
         'path': 'models/i3d/checkpoints/i3d_rgb.pt'},
        {'kind': 'bundled', 'name': 'i3d_flow.pt', 'convert': 'plain',
         'path': 'models/i3d/checkpoints/i3d_flow.pt'},
    ],
    'raft': [
        {'kind': 'bundled', 'name': 'raft-sintel.pth', 'convert': 'plain',
         'path': 'models/raft/checkpoints/raft-sintel.pth'},
        {'kind': 'bundled', 'name': 'raft-kitti.pth', 'convert': 'plain',
         'path': 'models/raft/checkpoints/raft-kitti.pth'},
    ],
    's3d': [
        {'kind': 'bundled', 'name': 'S3D_kinetics400_torchified.pt',
         'convert': 'plain',
         'path': 'models/s3d/checkpoint/S3D_kinetics400_torchified.pt'},
    ],
}


def sha256_of(path: Path) -> str:
    h = hashlib.sha256()
    with open(path, 'rb') as f:
        for chunk in iter(lambda: f.read(1 << 20), b''):
            h.update(chunk)
    return h.hexdigest()


def expected_hash(art: dict) -> Optional[str]:
    """Full sha256, or the torch-hub 8-hex filename prefix, or None."""
    spec = art.get('sha256')
    if spec == 'filename':
        stem = Path(art['name']).stem
        return stem.rsplit('-', 1)[-1] if '-' in stem else None
    return spec


def verify(path: Path, art: dict) -> bool:
    want = expected_hash(art)
    if want is None:
        return path.exists()
    return path.exists() and sha256_of(path).startswith(want)


def download(url: str, dest: Path) -> None:
    dest.parent.mkdir(parents=True, exist_ok=True)
    tmp = dest.with_suffix(dest.suffix + '.part')
    with urllib.request.urlopen(url) as src, open(tmp, 'wb') as out:
        shutil.copyfileobj(src, out, length=1 << 20)
    tmp.rename(dest)


def rebase(url: str, url_base: Optional[str]) -> str:
    """Swap the URL origin for a mirror base (``file:///...`` works)."""
    if not url_base:
        return url
    from urllib.parse import urlsplit
    parts = urlsplit(url)
    return url_base.rstrip('/') + parts.path


def fetch_artifact(art: dict, out: Path, url_base: Optional[str] = None,
                   checkout: Optional[Path] = None) -> Path:
    """Obtain one artifact into ``out`` and verify; returns the local path."""
    dest = out / art['name']
    if verify(dest, art):
        print(f'  {art["name"]}: present, checksum ok')
        return dest
    if art['kind'] == 'url':
        url = rebase(art['url'], url_base)
        print(f'  {art["name"]}: downloading {url}')
        download(url, dest)
        if not verify(dest, art):
            dest.unlink()
            raise RuntimeError(
                f'{art["name"]}: sha256 mismatch after download '
                f'(expected {expected_hash(art)})')
    elif art['kind'] == 'hub':
        print(f'  {art["name"]}: torch.hub.load({art["repo"]!r}, '
              f'{art["model"]!r})')
        import torch
        model = torch.hub.load(art['repo'], model=art['model'],
                               num_classes=art['num_classes'],
                               pretrained=True)
        dest.parent.mkdir(parents=True, exist_ok=True)
        torch.save(model.state_dict(), dest)
    elif art['kind'] == 'bundled':
        if checkout is None:
            raise RuntimeError(
                f'{art["name"]} has no public URL (the reference bundles it '
                f'in-repo at {art["path"]}); pass --from-checkout '
                f'<path to a video_features checkout>')
        src = checkout / art['path']
        if not src.exists():
            raise RuntimeError(f'{src} not found in checkout')
        dest.parent.mkdir(parents=True, exist_ok=True)
        shutil.copyfile(src, dest)
        print(f'  {art["name"]}: copied from {src}')
    else:  # pragma: no cover
        raise ValueError(art['kind'])
    return dest


def convert_artifact(src: Path, recipe: str) -> Path:
    """.pt/.pth → torch-free .npz next to it, per-family recipe."""
    from video_features_tpu.transplant.torch2jax import (
        load_torch_checkpoint, save_transplanted, transplant,
    )
    dst = src.with_suffix('.npz')
    if recipe == 'pca':
        # PCA params are plain arrays, not network weights: no transposes.
        import numpy as np
        import torch
        sd = torch.load(src, map_location='cpu', weights_only=False)
        np.savez(dst, **{k: np.asarray(v) for k, v in sd.items()})
    elif recipe == 'clip_jit':
        import numpy as np
        import torch

        from video_features_tpu.models import clip as clip_model
        try:  # OpenAI ships TorchScript archives
            sd = torch.jit.load(src, map_location='cpu').state_dict()
        except RuntimeError:
            sd = torch.load(src, map_location='cpu', weights_only=False)
            if hasattr(sd, 'state_dict'):
                sd = sd.state_dict()
        params = transplant(sd, no_transpose=set(clip_model.NO_TRANSPOSE),
                            dtype=np.float32)
        save_transplanted(params, str(dst))
    else:
        save_transplanted(load_torch_checkpoint(str(src)), str(dst))
    print(f'  {src.name} → {dst.name}')
    return dst


def fetch(families: List[str], out: Path, convert: bool = True,
          url_base: Optional[str] = None,
          checkout: Optional[Path] = None) -> List[Path]:
    got = []
    for fam in families:
        print(f'[{fam}]')
        for art in SOURCES[fam]:
            path = fetch_artifact(art, out, url_base, checkout)
            if convert:
                path = convert_artifact(path, art['convert'])
            got.append(path)
    return got


def main() -> int:
    ap = argparse.ArgumentParser(
        description=__doc__, formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument('families', nargs='+',
                    help=f'feature families, or "all": {", ".join(SOURCES)}')
    ap.add_argument('--out', default='./checkpoints', type=Path)
    ap.add_argument('--no-convert', action='store_true',
                    help='keep raw torch files; skip the .npz conversion')
    ap.add_argument('--url-base', default=None,
                    help='mirror origin replacing each URL host '
                         '(file:///local/mirror works)')
    ap.add_argument('--from-checkout', default=None, type=Path,
                    help='existing video_features checkout holding the '
                         'bundled i3d/raft/s3d blobs')
    ns = ap.parse_args()

    fams = list(SOURCES) if ns.families == ['all'] else ns.families
    unknown = [f for f in fams if f not in SOURCES]
    if unknown:
        ap.error(f'unknown families: {unknown}; known: {", ".join(SOURCES)}')
    got = fetch(fams, ns.out, convert=not ns.no_convert,
                url_base=ns.url_base, checkout=ns.from_checkout)
    print(f'{len(got)} artifacts ready under {ns.out}')
    return 0


if __name__ == '__main__':
    raise SystemExit(main())
