#!/usr/bin/env python3
"""Offline maintenance for the persistent executable store (aot/).

The online path (``aot/store.py``) only evicts inline when a publish
pushes the store over ``aot_max_bytes`` and only size-checks payloads it
is about to serve; this tool is the periodic/cron surface that does the
rest:

  * compacts the append-only ``manifest.jsonl`` (put/touch/del op log)
    down to one line per live entry — a frequently booted host's
    manifest otherwise grows with every load;
  * evicts LRU entries down to ``--target-bytes`` (oldest-loaded first
    — executables for retired configs/jax versions age out naturally);
  * ``--verify`` re-hashes every stored payload against its recorded
    SHA-256 (not just the size check) and evicts mismatches — bit-rot
    the online size check cannot see;
  * removes orphaned object directories (crashed writers).

Safe to run against a live store dir: all mutations go through the same
process-atomic store operations, and concurrent readers degrade evicted
entries to compile-on-miss.

Usage:
    python tools/aot_gc.py --aot-dir ~/.cache/video_features_tpu/executables \\
        [--target-bytes 10000000000] [--verify] [--no-compact]

Prints one JSON report line on stdout. Exit codes:
    0  clean — no corrupt entries found
    1  corrupt/truncated entries were found (and evicted)
    2  usage error (missing/invalid --aot-dir, bad --target-bytes)
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument('--aot-dir', required=True,
                    help='the executable store directory (aot_dir '
                         'config key)')
    ap.add_argument('--target-bytes', type=int, default=None,
                    help='evict LRU entries until total stored bytes <= N '
                         '(default: no size pressure)')
    ap.add_argument('--verify', action='store_true',
                    help='re-hash every stored payload against its '
                         'recorded SHA-256 (slower; catches silent bit '
                         'rot the size check cannot)')
    ap.add_argument('--no-compact', action='store_true',
                    help='skip the manifest rewrite (report/evict only)')
    ns = ap.parse_args(argv)

    aot_dir = os.path.abspath(os.path.expanduser(ns.aot_dir))
    if not os.path.isdir(aot_dir):
        print(f'error: --aot-dir {ns.aot_dir!r} is not a directory',
              file=sys.stderr)
        return 2
    if ns.target_bytes is not None and ns.target_bytes < 0:
        print('error: --target-bytes must be >= 0', file=sys.stderr)
        return 2

    # a fresh instance, NOT ExecStore.get: the offline tool must read
    # the manifest as it is on disk, not this process's live view
    from video_features_tpu.aot.store import ExecStore
    store = ExecStore(aot_dir)
    report = store.gc(target_bytes=ns.target_bytes, verify=ns.verify,
                      compact=not ns.no_compact)
    report['aot_dir'] = aot_dir
    report['verified'] = bool(ns.verify)
    print(json.dumps(report, sort_keys=True))
    return 1 if report['corrupt_evicted'] else 0


if __name__ == '__main__':
    raise SystemExit(main())
