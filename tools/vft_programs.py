#!/usr/bin/env python3
"""vft-programs launcher: ``python tools/vft_programs.py [flags]``.

A thin wrapper over ``python -m video_features_tpu.analysis.programs``
that works from a source checkout without installation and pins the
analysis environment BEFORE jax initializes:

  * ``JAX_PLATFORMS=cpu`` — the checker lowers programs abstractly; it
    must never dial real hardware (a remote-TPU tunnel can block a
    pure-CPU check for minutes);
  * ``--xla_force_host_platform_device_count=2`` (appended to
    ``XLA_FLAGS`` unless the caller already forces a count) — the
    mesh-width-2 lock variants need two host devices to build their
    data mesh.

Exit codes (shared contract, analysis/core.py): 0 clean, 1 analyzer
error, 2 lock drift or a new rule finding. Unlike vft-lint there is no
exit 3 — this tool NEEDS jax by design; its purity bar is "no device
execution", which lowering guarantees structurally.
"""
import os

from _bootstrap import add_repo_root

# unconditional, not setdefault: a host-wide JAX_PLATFORMS=tpu export
# would otherwise lower on real hardware — different StableHLO than the
# CPU-pinned committed lock (spurious drift) AND a dialed tunnel. A
# deliberate non-cpu check can call `-m ...analysis.programs` directly.
os.environ['JAX_PLATFORMS'] = 'cpu'
_xla_flags = os.environ.get('XLA_FLAGS', '')
if '--xla_force_host_platform_device_count' not in _xla_flags:
    os.environ['XLA_FLAGS'] = (
        _xla_flags + ' --xla_force_host_platform_device_count=2').strip()

add_repo_root()

from video_features_tpu.analysis.programs import main  # noqa: E402

if __name__ == '__main__':
    import sys
    sys.exit(main())
