#!/usr/bin/env python3
"""Precision ladder for the R(2+1)D lane: drift + in-graph clips/sec.

BASELINE.md names R(2+1)D as the second north-star model; this tool
produces the data behind its bench rung's precision stamp (the i3d ladder
in tools/precision_study.py does NOT transfer: r21d has no flow-quantization
cliff, so bf16 passes may well meet the ≤1e-3 parity bar that the fused
i3d path fails at 1-pass).

For each matmul precision ('highest', 'high', 'default') it runs the
PRODUCTION r21d device step (extract.r21d.ExtractR21D._forward_batch —
transforms + network, the same jit'd fn the extractor calls) on identical
uint8-valued frames + seeded weights, and prints one JSON line per rung:
feature rel L2 vs the 'highest' baseline and in-graph clips/sec (bench.py
methodology: lax.scan over distinct batches inside one jit, value fetch).

    python tools/r21d_precision_study.py             # on the default device
    BENCH_PLATFORM=cpu python tools/r21d_precision_study.py   # smoke
"""
from __future__ import annotations

import json
import os
import sys
import time
from functools import partial
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

LADDER = ('highest', 'high', 'default')


def main() -> None:
    import jax

    if os.environ.get('BENCH_PLATFORM'):
        jax.config.update('jax_platforms', os.environ['BENCH_PLATFORM'])
    import jax.numpy as jnp
    from jax import lax

    from video_features_tpu.extract.r21d import ExtractR21D
    from video_features_tpu.models import r21d as r21d_model
    from video_features_tpu.transplant.torch2jax import transplant
    from video_features_tpu.utils.device import (
        enable_compilation_cache, jax_device,
    )

    platform = jax.devices()[0].platform
    on_accel = platform != 'cpu'
    arch = os.environ.get('R21D_ARCH', 'r2plus1d_18')
    stack = int(os.environ.get('BENCH_STACK', 16))
    # decode-size frames: the reference sample video is 340x256 and the
    # transform chain resizes to (128, 171) in-graph, so the honest input
    # is the decoded geometry, not the network's 112px crop
    h, w = (256, 340) if on_accel else (64, 86)
    batch = int(os.environ.get('BENCH_BATCH', 16 if on_accel else 2))
    iters = int(os.environ.get('BENCH_ITERS', 8 if on_accel else 2))
    enable_compilation_cache('~/.cache/video_features_tpu/xla', platform)

    device = jax_device(platform)
    params = jax.device_put(
        transplant(r21d_model.init_state_dict(arch=arch)), device)
    rng = np.random.RandomState(0)
    frames = jax.device_put(
        rng.randint(0, 255, size=(iters, batch, stack, h, w, 3))
        .astype(np.float32), device)
    step = partial(ExtractR21D._forward_batch, arch=arch)

    def run(precision: str):
        def chained(p, xs):
            def body(_, stacks):
                with jax.default_matmul_precision(precision):
                    return None, step(p, stacks)
            _, feats = lax.scan(body, None, xs)
            return feats
        jitted = jax.jit(chained)
        feats = np.asarray(jitted(params, frames))       # compile + warm
        assert np.isfinite(feats).all()
        t0 = time.perf_counter()
        feats = np.asarray(jitted(params, frames))       # value fetch = real
        elapsed = time.perf_counter() - t0
        return feats, batch * iters / elapsed

    base, _ = run('highest')
    for precision in LADDER:
        feats, rate = run(precision)
        drift = float(np.linalg.norm(feats - base) / np.linalg.norm(base))
        print(json.dumps({
            'arch': arch, 'precision': precision, 'platform': platform,
            'stack': stack, 'input_px': [h, w], 'batch': batch,
            'feature_rel_l2_vs_highest': float(f'{drift:.3e}'),
            'clips_per_sec': round(rate, 2),
        }))


if __name__ == '__main__':
    main()
