#!/usr/bin/env python3
"""Precision ladder for the R(2+1)D lane — see family_precision_study.py.

Kept as the documented entry point for the second north-star model
(BASELINE.md; bench.py's r21d rungs cite this tool): it now delegates to
the generalized tools/family_precision_study.py so there is exactly one
copy of the ladder methodology. Knobs are unchanged:

    python tools/r21d_precision_study.py               # r2plus1d_18, v5e
    R21D_ARCH=r2plus1d_34 BENCH_STACK=32 python tools/r21d_precision_study.py
    BENCH_PLATFORM=cpu python tools/r21d_precision_study.py   # smoke

Measured on v5e (stack 16, 340x256 decode-geometry frames, batch 16):
'mixed'(=high) drift 2.0e-4 vs float32 — parity-grade — at ~253
clips/s/chip; 'default' 3.1e-3 (fails the 1e-3 bar) at ~446. The ig65m
r2plus1d_34 at stack 32: mixed 3.9e-4 at ~91 clips/s, default 6.9e-3.
"""
from __future__ import annotations

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

if __name__ == '__main__':
    from tools.family_precision_study import main

    sys.argv = [sys.argv[0], 'r21d']
    main()
