#!/usr/bin/env python3
"""vft-wire launcher: ``python tools/vft_wire.py [flags]``.

A thin wrapper over ``python -m video_features_tpu.analysis.wire`` that
works from a source checkout without installation (repo-root resolution
shared with vft-lint/vft-programs via ``_bootstrap``). Like vft-lint,
the checker is pure-AST: it parses the wire surface — the loopback
protocol, ``ServeClient``, the ingress routes — and never imports any
of it; the snapshot below is taken BEFORE the first package import so a
jax import sneaking into the ``__init__`` chain trips the exit-3 guard
honestly even on jax-resident hosts.

Exit codes (analysis/core.py contract): 0 clean, 1 analyzer error,
2 lock drift / new finding, 3 jax imported.
"""
import sys

from _bootstrap import add_repo_root

# honest purity probe: BEFORE the package (or anything else) is imported
_JAX_PRELOADED = 'jax' in sys.modules

add_repo_root()

from video_features_tpu.analysis.wire import main  # noqa: E402

if __name__ == '__main__':
    sys.exit(main(jax_preloaded=_JAX_PRELOADED))
