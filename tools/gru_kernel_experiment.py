#!/usr/bin/env python3
"""Measured answer to "would a fused GRU Pallas kernel beat XLA?": NO.

Implements ONE SepConvGRU direction (reference update.py:39-77, the 1x5
pass — zr gate conv + q conv + sigmoid/tanh gating, 45% of the refinement
iteration's FLOPs and its cleanest structure) as a Mosaic kernel:

  * a (P pairs x HB rows) activation block resident in VMEM (the 1x5 conv
    has no H halo, so H blocks freely);
  * inputs hi/lo-split to bf16 ONCE per buffer; each of the 5 conv taps is
    3 bf16 MXU dots (manual bf16_3x == XLA 'high' — Mosaic does not expose
    multi-pass precision natively);
  * the tap window slides over the LEADING (untiled) buffer dim so dynamic
    slices need no sublane alignment;
  * gating fused in-kernel, one f32 write per output.

Result on v5e (2026-07-31, B=256 pairs, 28x28 maps, 30-iteration scan):

    xla conv direction (precision 'high'):  2.72 ms
    this kernel        (manual bf16_3x):    2.71 ms

i.e. XLA's implicit-GEMM conv + fused epilogues already sits at the
hand-kernel frontier for these shapes. Together with the precision sweep
(tools/precision_study.py: no component tolerates 1-pass) this closes the
"build a per-iteration GRU fusion" question — the mixed/default gap is
3-pass bf16 arithmetic, not a schedulable kernel win. Full analysis:
docs/benchmarks.md "Why a fused GRU kernel does not close the gap".
"""
import time
import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from video_features_tpu.utils.device import enable_compilation_cache, jax_device

platform = jax.devices()[0].platform
enable_compilation_cache('~/.cache/video_features_tpu/xla', platform)
dev = jax_device(platform)
interpret = platform != 'tpu'

B, H, W, C = 256, 28, 28, 128   # pairs, map, hidden dim
CM = 2 * C                       # hm channels
P, HB = 4, 7                     # block: P pairs x HB rows
K = 5                            # tap count
PREC = jax.lax.Precision.HIGH

rng = np.random.RandomState(0)
h = jax.device_put(np.tanh(rng.randn(B, H, W, C)).astype(np.float32), dev)
motion = jax.device_put(rng.randn(B, H, W, C).astype(np.float32), dev)
Wzr = jax.device_put((rng.randn(K, CM, CM) * 0.05).astype(np.float32), dev)
Wq = jax.device_put((rng.randn(K, CM, C) * 0.05).astype(np.float32), dev)
zr_term = jax.device_put((rng.randn(B, H, W, CM) * 0.1).astype(np.float32), dev)
q_term = jax.device_put((rng.randn(B, H, W, C) * 0.1).astype(np.float32), dev)


def xla_direction(h, motion, Wzr, Wq, zr_term, q_term):
    with jax.default_matmul_precision('high'):
        hm = jnp.concatenate([h, motion], -1)
        hp = jnp.pad(hm, [(0, 0), (0, 0), (2, 2), (0, 0)])
        zr = zr_term
        for s in range(K):
            zr = zr + jnp.einsum('bhwc,cn->bhwn', hp[:, :, s:s + W], Wzr[s],
                                 precision=PREC)
        zr = jax.nn.sigmoid(zr)
        z, r = jnp.split(zr, 2, -1)
        rhm = jnp.concatenate([r * h, motion], -1)
        rp = jnp.pad(rhm, [(0, 0), (0, 0), (2, 2), (0, 0)])
        q = q_term
        for s in range(K):
            q = q + jnp.einsum('bhwc,cn->bhwn', rp[:, :, s:s + W], Wq[s],
                               precision=PREC)
        q = jnp.tanh(q)
        return (1 - z) * h + z * q


def xla_conv_direction(h, motion, Wzr, Wq, zr_term, q_term):
    from video_features_tpu.ops.nn import conv
    with jax.default_matmul_precision('high'):
        hm = jnp.concatenate([h, motion], -1)
        zr = conv(hm, Wzr.transpose(1, 0, 2).reshape(1, K, CM, CM),
                  padding=[(0, 0), (2, 2)]) + zr_term
        zr = jax.nn.sigmoid(zr)
        z, r = jnp.split(zr, 2, -1)
        q = conv(jnp.concatenate([r * h, motion], -1),
                 Wq.transpose(1, 0, 2).reshape(1, K, CM, C),
                 padding=[(0, 0), (2, 2)]) + q_term
        q = jnp.tanh(q)
        return (1 - z) * h + z * q


# ------------------------------------------------------------- the kernel --
def _split(x):
    xh = x.astype(jnp.bfloat16)
    xl = (x - xh.astype(jnp.float32)).astype(jnp.bfloat16)
    return xh, xl


def _band_matmul(bh_ref, bl_ref, w_h_ref, w_l_ref, acc):
    """acc += 1x5 conv of the (W+4, M, CM) padded hi/lo scratch refs with
    the (K, CM, n_out) hi/lo weights — per tap, 3 bf16 dots (bf16_3x).
    The sliding dim is LEADING (untiled), so dynamic taps need no sublane
    alignment."""
    M = bh_ref.shape[1]

    def tap(s, acc):
        sh = bh_ref[pl.ds(s, W)].reshape(W * M, CM)
        sl = bl_ref[pl.ds(s, W)].reshape(W * M, CM)
        wh = w_h_ref[s]
        wl = w_l_ref[s]
        acc += jnp.dot(sh, wh, preferred_element_type=jnp.float32)
        acc += jnp.dot(sh, wl, preferred_element_type=jnp.float32)
        acc += jnp.dot(sl, wh, preferred_element_type=jnp.float32)
        return acc

    return lax.fori_loop(0, K, tap, acc)


def _kernel(h_ref, m_ref, zrt_ref, qt_ref, wzrh_ref, wzrl_ref,
            wqh_ref, wql_ref, out_ref, bh_ref, bl_ref):
    # everything in (W, M, C) layout: W leads so the conv taps slide over
    # an untiled dim; one transpose in, one out
    M = P * HB
    h = h_ref[:].reshape(M, W, C).swapaxes(0, 1)           # (W, M, C)
    m = m_ref[:].reshape(M, W, C).swapaxes(0, 1)
    zrt = zrt_ref[:].reshape(M, W, CM).swapaxes(0, 1).reshape(W * M, CM)
    qt = qt_ref[:].reshape(M, W, C).swapaxes(0, 1).reshape(W * M, C)
    zpad = jnp.zeros((2, M, CM), jnp.bfloat16)

    hm_h, hm_l = _split(jnp.concatenate([h, m], -1))
    bh_ref[0:2] = zpad
    bl_ref[0:2] = zpad
    bh_ref[W + 2:] = zpad
    bl_ref[W + 2:] = zpad
    bh_ref[2:W + 2] = hm_h
    bl_ref[2:W + 2] = hm_l
    zr = _band_matmul(bh_ref, bl_ref, wzrh_ref, wzrl_ref, zrt)
    zr = jax.nn.sigmoid(zr).reshape(W, M, CM)
    z = zr[:, :, :C]
    r = zr[:, :, C:]

    rhm_h, rhm_l = _split(jnp.concatenate([r * h, m], -1))
    bh_ref[2:W + 2] = rhm_h
    bl_ref[2:W + 2] = rhm_l
    q = _band_matmul(bh_ref, bl_ref, wqh_ref, wql_ref, qt)
    q = jnp.tanh(q).reshape(W, M, C)

    out = (1 - z) * h + z * q                              # (W, M, C)
    out_ref[:] = out.swapaxes(0, 1).reshape(P, HB, W, C)


def pallas_direction(h, motion, Wzr, Wq, zr_term, q_term):
    grid = (B // P, H // HB)
    blk = lambda c: pl.BlockSpec((P, HB, W, c), lambda i, j: (i, j, 0, 0),
                                 memory_space=pltpu.VMEM)
    wspec = lambda shape: pl.BlockSpec(shape, lambda i, j: (0,) * len(shape),
                                       memory_space=pltpu.VMEM)
    wzrh, wzrl = _split(Wzr)
    wqh, wql = _split(Wq)
    return pl.pallas_call(
        _kernel,
        grid=grid,
        in_specs=[blk(C), blk(C), blk(CM), blk(C),
                  wspec((K, CM, CM)), wspec((K, CM, CM)),
                  wspec((K, CM, C)), wspec((K, CM, C))],
        out_specs=blk(C),
        out_shape=jax.ShapeDtypeStruct((B, H, W, C), jnp.float32),
        scratch_shapes=[pltpu.VMEM((W + 4, P * HB, CM), jnp.bfloat16),
                        pltpu.VMEM((W + 4, P * HB, CM), jnp.bfloat16)],
        interpret=interpret,
    )(h, motion, zr_term, q_term, wzrh, wzrl, wqh, wql)


def bench(fn, iters=30):
    j = jax.jit(lambda *a: lax.scan(
        lambda acc, _: (acc + fn(*a).sum(), None),
        jnp.float32(0), None, length=iters)[0])
    float(j(h, motion, Wzr, Wq, zr_term, q_term))
    t0 = time.perf_counter()
    float(j(h, motion, Wzr, Wq, zr_term, q_term))
    return (time.perf_counter() - t0) / iters * 1000


ref = np.asarray(jax.jit(xla_direction)(h, motion, Wzr, Wq, zr_term, q_term))
got = np.asarray(jax.jit(pallas_direction)(h, motion, Wzr, Wq, zr_term, q_term))
rel = np.linalg.norm(got - ref) / np.linalg.norm(ref)
print(f'kernel vs xla rel L2: {rel:.2e}')
print(f'xla einsum direction: {bench(xla_direction):.2f} ms')
print(f'xla conv   direction: {bench(xla_conv_direction):.2f} ms')
print(f'pallas     direction: {bench(pallas_direction):.2f} ms')
