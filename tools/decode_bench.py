#!/usr/bin/env python3
"""Host decode throughput: native C++ decoder vs cv2, and worker scaling.

SURVEY hard-part #3: at pod scale the wall is host decode, not device
compute. This tool measures, on a real clip:

  * raw decode frames/s per backend ('native' in-process libav vs 'cv2')
    — the per-video ceiling (one coded stream decodes sequentially);
  * decode + host-transform (short-side resize 256, the reference's i3d
    preprocessing) frames/s as ``decode_workers`` scales 1→8 — the
    transform pool is what actually parallelizes (VideoLoader's
    ``transform_workers``);
  * the implied e2e clips/s-per-host ceiling at stack 16.

One JSON line per measurement. Results are published in
docs/benchmarks.md ("Host decode throughput").

    python tools/decode_bench.py [--video PATH] [--repeat 3]
"""
from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))


def _video(path: str | None) -> str:
    if path:
        return path
    ref = Path('/root/reference/sample/v_GGSY1Qvo990.mp4')
    if ref.exists():
        return str(ref)
    import subprocess
    out = Path('./tmp/decode_bench/sample_moving_pattern.mp4')
    if not out.exists():
        subprocess.run(
            [sys.executable,
             str(Path(__file__).parent / 'make_sample_video.py'),
             '--out', str(out.parent), '--seconds', '10', '--fps', '25',
             '--size', '340x256'], check=True, stdout=sys.stderr)
    return str(out)


def bench_raw(video: str, backend: str, repeat: int) -> dict:
    """Raw sequential decode frames/s for one backend."""
    from video_features_tpu.io.video import VideoLoader

    rates = []
    frames = 0
    for _ in range(repeat):
        loader = VideoLoader(video, batch_size=32, backend=backend)
        t0 = time.perf_counter()
        frames = sum(b.shape[0] for b, _, _ in loader)
        rates.append(frames / (time.perf_counter() - t0))
    return {'measure': f'decode_raw_{backend}', 'frames': frames,
            'frames_per_sec': round(float(np.median(rates)), 1)}


def bench_transform(video: str, backend: str, workers: int,
                    repeat: int) -> dict:
    """Decode + short-side-resize-256 frames/s with a transform pool."""
    from video_features_tpu.io.video import VideoLoader
    from video_features_tpu.ops.transforms import short_side_resize_pil

    rates = []
    frames = 0
    for _ in range(repeat):
        loader = VideoLoader(
            video, batch_size=32, backend=backend,
            transform=lambda f: short_side_resize_pil(f, 256),
            transform_workers=workers)
        t0 = time.perf_counter()
        frames = sum(len(b) for b, _, _ in loader)
        rates.append(frames / (time.perf_counter() - t0))
    return {'measure': f'decode_resize256_{backend}_workers{workers}',
            'frames': frames,
            'frames_per_sec': round(float(np.median(rates)), 1)}


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument('--video', default=None)
    ap.add_argument('--repeat', type=int, default=3)
    ns = ap.parse_args()
    video = _video(ns.video)

    from video_features_tpu.io import native
    backends = ['cv2'] + (['native'] if native.available() else [])

    records = []
    for backend in backends:
        records.append(bench_raw(video, backend, ns.repeat))
    for backend in backends:
        for workers in (1, 2, 4, 8):
            records.append(bench_transform(video, backend, workers,
                                           ns.repeat))
    best = max(r['frames_per_sec'] for r in records
               if r['measure'].startswith('decode_resize256'))
    records.append({'measure': 'implied_e2e_ceiling_stack16',
                    'clips_per_sec_per_host': round(best / 16, 1),
                    'note': 'best decode+resize rate / 16-frame stacks; '
                            'multi-video worklists run one decoder per '
                            'process (shared-nothing DP), so per-host '
                            'throughput scales with processes until '
                            'cores saturate'})
    for r in records:
        print(json.dumps(r))
    return 0


if __name__ == '__main__':
    raise SystemExit(main())
