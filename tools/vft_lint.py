#!/usr/bin/env python3
"""vft-lint launcher: ``python tools/vft_lint.py [flags]``.

A thin wrapper over ``python -m video_features_tpu.analysis`` that works
from a source checkout without installation (it prepends the repo root
to ``sys.path``). The analyzer is pure-AST: it parses the package, never
imports it, and exits 3 if jax lands in the process — the snapshot below
is taken BEFORE any package import, so even a jax import sneaking into
``video_features_tpu/__init__.py``'s chain trips the check (the bare
``-m`` spelling can only catch imports that happen after the package
loaded).

Exit codes: 0 clean, 1 analyzer error, 2 new findings, 3 jax imported.
"""
import sys

from _bootstrap import add_repo_root

# honest purity probe: BEFORE the package (or anything else) is imported
_JAX_PRELOADED = 'jax' in sys.modules

add_repo_root()

from video_features_tpu.analysis.__main__ import main  # noqa: E402

if __name__ == '__main__':
    sys.exit(main(jax_preloaded=_JAX_PRELOADED))
