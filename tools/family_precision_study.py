#!/usr/bin/env python3
"""Precision ladder across model families: drift + in-graph rate.

Generalizes tools/r21d_precision_study.py to every family with a dense
device step (r21d, s3d, resnet50, clip ViT-B/32, vggish): for each
matmul precision it runs the PRODUCTION extractor step (transforms +
network, the exact jit'd fn the extractor calls) on identical inputs +
seeded weights and prints one JSON line per (family, precision): feature
rel L2 vs the 'highest' baseline and the in-graph rate (bench.py
methodology — lax.scan over distinct batches inside one jit, value
fetch). Inputs match each step's production range as well as geometry
(0-255 frames for the vision families, log-mel-scaled values for
vggish — bf16 drift depends on activation magnitude).

Stack families (r21d, s3d) report clips (stacks) per second; frame-wise
families (resnet, clip) report frames per second; vggish reports 0.96 s
log-mel examples per second. `BENCH_STACK` overrides
the stack length and `R21D_ARCH` the r21d variant (the knobs
tools/r21d_precision_study.py documents).

    python tools/family_precision_study.py [families...]
    BENCH_PLATFORM=cpu python tools/family_precision_study.py s3d  # smoke
"""
from __future__ import annotations

import json
import math
import os
import sys
import time
from functools import partial
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

LADDER = ('highest', 'high', 'default')


def _family_specs(on_accel: bool):
    """{name: (init_fn, step_fn, batch_shape, unit, input_map,
    count_per_batch)} — step fns are the extractors' own; input geometry
    AND value range mirror what each step receives in production
    (decode-geometry 0-255 stacks for the in-graph-resizing stack
    families, host-cropped 0-255 frames for the frame-wise ones,
    log-mel-range examples for vggish — input_map rescales the shared
    random tensor host-side). count_per_batch is the work-unit count one
    step produces (None → batch_shape[0]; raft's B+1 frames make B
    flows)."""
    from video_features_tpu.extract.clip import ExtractCLIP
    from video_features_tpu.extract.r21d import ExtractR21D
    from video_features_tpu.extract.raft import ExtractRAFT
    from video_features_tpu.extract.resnet import ExtractResNet
    from video_features_tpu.extract.s3d import ExtractS3D
    from video_features_tpu.models import clip as clip_model
    from video_features_tpu.models import r21d as r21d_model
    from video_features_tpu.models import raft as raft_model
    from video_features_tpu.models import resnet as resnet_model
    from video_features_tpu.models import s3d as s3d_model
    from video_features_tpu.models import vggish as vggish_model

    h, w = (256, 340) if on_accel else (64, 86)
    stack = int(os.environ.get('BENCH_STACK', 16))
    r21d_arch = os.environ.get('R21D_ARCH', 'r2plus1d_18')
    b_stack = 16 if on_accel else 1
    b_frame = 64 if on_accel else 2
    px = 224 if on_accel else 64
    # CLIP's positional embedding fixes its input at 224, and s3d's
    # in-graph center_crop is fixed at 224 (a smaller smoke frame would
    # exercise a clamped crop production never sees) — shrink the batch,
    # not the geometry, for smoke runs
    clip_px, clip_b = 224, (b_frame if on_accel else 1)
    s3d_h, s3d_w = (h, w) if on_accel else (256, 340)
    s3d_scale = 224 / min(s3d_h, s3d_w)
    s3d_hw = (math.floor(s3d_h * s3d_scale), math.floor(s3d_w * s3d_scale))
    # the VGG step consumes log-mel values log(mel + 0.01) ≈ [-4.6, 5]
    # directly (no in-graph normalization) — map the shared 0-255 tensor
    # into that range so drift is measured at production magnitude
    def log_mel_range(x):
        return x / 255.0 * 9.6 - 4.6

    # raft-as-feature-type (flow fields out, reference models/raft/
    # extract_raft.py:12-29): native-resolution geometry — the sample's
    # 256x340 short-side-256 frame padded to /8 (256x344), B+1 frames in
    # one extractor step -> B flows via forward_consecutive
    raft_h, raft_w = (256, 344) if on_accel else (64, 88)
    raft_b = (16 if on_accel else 2) + 1

    return {
        'r21d': (
            partial(r21d_model.init_state_dict, arch=r21d_arch),
            partial(ExtractR21D._forward_batch, arch=r21d_arch),
            (b_stack, stack, h, w, 3), 'clips/sec', None, None),
        's3d': (
            s3d_model.init_state_dict,
            partial(ExtractS3D._forward, resize_hw=s3d_hw,
                    resize_scale=s3d_scale),
            (b_stack, stack, s3d_h, s3d_w, 3), 'clips/sec', None, None),
        'resnet': (
            partial(resnet_model.init_state_dict, arch='resnet50'),
            partial(ExtractResNet._forward, arch='resnet50'),
            (b_frame, px, px, 3), 'frames/sec', None, None),
        'clip': (
            partial(clip_model.init_state_dict, model_name='ViT-B/32'),
            partial(ExtractCLIP._forward, arch='ViT-B/32'),
            (clip_b, clip_px, clip_px, 3), 'frames/sec', None, None),
        'vggish': (
            vggish_model.init_state_dict,
            vggish_model.forward,
            (b_frame, 96, 64, 1), 'examples/sec', log_mel_range, None),
        'raft': (
            raft_model.init_state_dict,
            partial(ExtractRAFT._flow_batch, iters=raft_model.ITERS),
            (raft_b, raft_h, raft_w, 3), 'flows/sec', None, raft_b - 1),
    }


def run_family(name: str, init_fn, step_fn, batch_shape, unit,
               input_map, count_per_batch, iters: int) -> None:
    import jax
    from jax import lax

    from video_features_tpu.transplant.torch2jax import transplant
    from video_features_tpu.utils.device import jax_device

    platform = jax.devices()[0].platform
    device = jax_device(platform)
    params = jax.device_put(transplant(init_fn()), device)
    rng = np.random.RandomState(0)
    raw = rng.randint(0, 255,
                      size=(iters,) + batch_shape).astype(np.float32)
    if input_map is not None:     # host-side: production value range
        raw = input_map(raw).astype(np.float32)
    frames = jax.device_put(raw, device)

    def run(precision):
        def chained(p, xs):
            def body(_, batch):
                with jax.default_matmul_precision(precision):
                    return None, step_fn(p, batch)
            _, feats = lax.scan(body, None, xs)
            return feats
        jitted = jax.jit(chained)
        feats = np.asarray(jitted(params, frames))       # compile + warm
        assert np.isfinite(feats).all()
        t0 = time.perf_counter()
        feats = np.asarray(jitted(params, frames))
        elapsed = time.perf_counter() - t0
        count = (count_per_batch if count_per_batch is not None
                 else batch_shape[0])
        return feats, count * iters / elapsed

    base, _ = run('highest')
    for precision in LADDER:
        feats, rate = run(precision)
        drift = float(np.linalg.norm(feats - base) / np.linalg.norm(base))
        print(json.dumps({
            'family': name, 'precision': precision, 'platform': platform,
            'batch_shape': list(batch_shape),
            'feature_rel_l2_vs_highest': float(f'{drift:.3e}'),
            'rate': round(rate, 2), 'unit': unit,
        }), flush=True)


def main() -> None:
    import jax

    if os.environ.get('BENCH_PLATFORM'):
        jax.config.update('jax_platforms', os.environ['BENCH_PLATFORM'])
    from video_features_tpu.utils.device import enable_compilation_cache

    platform = jax.devices()[0].platform
    on_accel = platform != 'cpu'
    enable_compilation_cache('~/.cache/video_features_tpu/xla', platform)
    iters = int(os.environ.get('BENCH_ITERS', 8 if on_accel else 2))

    specs = _family_specs(on_accel)
    picks = sys.argv[1:] or list(specs)
    for name in picks:
        run_family(name, *specs[name], iters)


if __name__ == '__main__':
    main()
