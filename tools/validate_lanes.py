#!/usr/bin/env python3
"""Validate the TPU corr-lookup kernels at FULL production depth.

tests/test_pallas_corr.py compares the lanes/pallas kernels against the
gather oracle at reduced GRU iterations (fp-noise amplifies under random
weights — see ops/pallas_corr.py); this tool runs the three lookup
implementations through the complete 20-iteration RAFT forward at CLI
geometry (256×344) on real hardware and reports their mutual drift.

Automated coverage of the same property lives in
tests/test_pallas_corr.py::test_lanes_full_depth_* — an interpret-mode
reduced-geometry variant in the slow lane plus a `-m tpu` real-hardware
variant that calls :func:`measure_drift` exactly like this CLI does.

Measured on v5e (2026-07-31, precision=highest, seeded weights):
    lanes  vs dense: rel L2 3.2e-05
    gather vs dense: rel L2 3.0e-05
i.e. the lane-packed production kernel sits at the same fp-noise floor as
the XLA gather oracle — the 20-iteration behavior is validated directly,
not just transitively through few-iteration tests.
"""
from __future__ import annotations

import os
import sys
from pathlib import Path
from typing import Dict, Sequence

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))


def measure_drift(h: int = 256, w: int = 344,
                  impls: Sequence[str] = ('dense', 'lanes', 'gather'),
                  iters: int = 20, precision: str = 'highest',
                  platform: str = None) -> Dict[str, float]:
    """Full-depth RAFT forward under each lookup impl → rel L2 vs the
    first impl. Frames are a smooth pattern with a second frame shifted by
    noise, 4× upsampled so bilinear lookups exercise fractional coords."""
    import jax

    from video_features_tpu.models import raft as raft_model
    from video_features_tpu.transplant.torch2jax import transplant
    from video_features_tpu.utils.device import jax_device

    platform = platform or jax.devices()[0].platform
    dev = jax_device(platform)
    params = jax.device_put(transplant(raft_model.init_state_dict()), dev)
    rng = np.random.RandomState(0)
    assert h % 4 == 0 and w % 4 == 0, (h, w)
    base = rng.rand(1, h // 4, w // 4, 3) * 255
    up = np.ones((1, 4, 4, 1))
    f1 = np.kron(np.clip(base, 0, 255), up).astype(np.float32)
    f2 = np.kron(np.clip(base + rng.rand(1, h // 4, w // 4, 3) * 25, 0, 255),
                 up).astype(np.float32)
    f1, f2 = jax.device_put(f1, dev), jax.device_put(f2, dev)

    outs = {}
    saved = os.environ.get('VFT_RAFT_LOOKUP')
    try:
        with jax.default_matmul_precision(precision):
            for impl in impls:
                os.environ['VFT_RAFT_LOOKUP'] = impl
                fn = jax.jit(lambda p, a, b: raft_model.forward(
                    p, a, b, iters=iters, platform=platform))
                outs[impl] = np.asarray(fn(params, f1, f2))
    finally:
        if saved is None:
            os.environ.pop('VFT_RAFT_LOOKUP', None)
        else:
            os.environ['VFT_RAFT_LOOKUP'] = saved
    ref = outs[impls[0]]
    return {impl: float(np.linalg.norm(outs[impl] - ref)
                        / np.linalg.norm(ref))
            for impl in impls[1:]}


def main() -> int:
    import jax

    from video_features_tpu.utils.device import enable_compilation_cache
    enable_compilation_cache('~/.cache/video_features_tpu/xla',
                             jax.devices()[0].platform)
    rels = measure_drift()
    ok = True
    for impl, rel in rels.items():
        print(f'{impl} vs dense @20 iters, highest, 256x344: '
              f'rel L2 = {rel:.3e}')
        ok &= rel < 1e-3
    return 0 if ok else 1


if __name__ == '__main__':
    raise SystemExit(main())
