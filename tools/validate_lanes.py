#!/usr/bin/env python3
"""Validate the TPU corr-lookup kernels at FULL production depth.

tests/test_pallas_corr.py compares the lanes/pallas kernels against the
gather oracle at reduced GRU iterations (fp-noise amplifies under random
weights — see ops/pallas_corr.py); this tool runs the three lookup
implementations through the complete 20-iteration RAFT forward at CLI
geometry (256×344) on real hardware and reports their mutual drift.

Measured on v5e (2026-07-31, precision=highest, seeded weights):
    lanes  vs dense: rel L2 3.2e-05
    gather vs dense: rel L2 3.0e-05
i.e. the lane-packed production kernel sits at the same fp-noise floor as
the XLA gather oracle — the 20-iteration behavior is validated directly,
not just transitively through few-iteration tests.
"""
from __future__ import annotations

import os
import sys
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))


def main() -> int:
    import jax

    from video_features_tpu.models import raft as raft_model
    from video_features_tpu.transplant.torch2jax import transplant
    from video_features_tpu.utils.device import (
        enable_compilation_cache, jax_device,
    )

    platform = jax.devices()[0].platform
    enable_compilation_cache('~/.cache/video_features_tpu/xla', platform)
    dev = jax_device(platform)
    params = jax.device_put(transplant(raft_model.init_state_dict()), dev)
    rng = np.random.RandomState(0)
    base = rng.rand(1, 64, 86, 3) * 255
    up = np.ones((1, 4, 4, 1))
    f1 = np.kron(np.clip(base, 0, 255), up).astype(np.float32)
    f2 = np.kron(np.clip(base + rng.rand(1, 64, 86, 3) * 25, 0, 255),
                 up).astype(np.float32)
    f1, f2 = jax.device_put(f1, dev), jax.device_put(f2, dev)

    outs = {}
    with jax.default_matmul_precision('highest'):
        for impl in ('dense', 'lanes', 'gather'):
            os.environ['VFT_RAFT_LOOKUP'] = impl
            fn = jax.jit(lambda p, a, b: raft_model.forward(
                p, a, b, platform=platform))
            outs[impl] = np.asarray(fn(params, f1, f2))
    ok = True
    for impl in ('lanes', 'gather'):
        rel = (np.linalg.norm(outs[impl] - outs['dense'])
               / np.linalg.norm(outs['dense']))
        print(f'{impl} vs dense @20 iters, highest, 256x344: '
              f'rel L2 = {rel:.3e}')
        ok &= rel < 1e-3
    return 0 if ok else 1


if __name__ == '__main__':
    raise SystemExit(main())
