#!/usr/bin/env python3
"""Calibrate a family's int8 weight lane and pin its scale table.

The int8 lane (``compute_dtype=int8``, ops/quant.py) quantizes conv/linear
weights per-output-channel at transplant time. The scales are
weight-derived (amax/127) and therefore deterministic, but this tool makes
them an EXPLICIT, pinned artifact:

  1. derives the per-tensor scale table from the checkpoint exactly as a
     build would (``ops/quant.derive_scales`` over the transplanted flat
     dict — same eligibility rule, same zero-guards);
  2. measures the family's feature rel-L2 drift (fp32 lane vs int8 lane,
     identical inputs — ``ops/precision.rel_l2``, the ONE parity metric)
     over N corpus videos, or over synthetic frame batches when no corpus
     is given;
  3. writes the table checkpoint-adjacent (``<ckpt>.int8-scales.npz``,
     ``ops/quant.scale_table_path``) with the measured drift in its
     metadata. Every subsequent build of that checkpoint on the int8 lane
     consumes the pinned table verbatim (torch2jax.load_torch_checkpoint)
     — reproducible across checkpoint re-exports that perturb weight
     bytes — and the measured number is checkable against the family's
     ``INT8_REL_L2_BOUNDS`` entry.

Prints ONE JSON line (the repo's bench/tool stdout contract): the family,
per-video drift, the pinned bound, and where the table landed.

    python tools/calibrate_int8.py resnet --checkpoint-path ck.pth \
        --videos a.mp4 b.mp4
    python tools/calibrate_int8.py clip            # synthetic calibration
"""
from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))


def build_lane(feature_type: str, compute_dtype: str, args_overrides,
               tmp_root: str):
    from video_features_tpu.config import load_config
    from video_features_tpu.registry import create_extractor
    overrides = {
        'video_paths': ['__calibrate_int8__.mp4'],
        'compute_dtype': compute_dtype,
        'output_path': f'{tmp_root}/out_{compute_dtype}',
        'tmp_path': f'{tmp_root}/tmp_{compute_dtype}',
    }
    overrides.update(args_overrides)
    return create_extractor(load_config(feature_type, overrides=overrides))


def synthetic_batches(ex, n: int, seed: int = 0):
    """N deterministic uint8 batches at the family's compiled geometry —
    the no-corpus fallback; weight-only quantization drift is
    input-robust, so synthetic frames rank scale tables faithfully even
    though a corpus measurement is the number to publish."""
    rng = np.random.RandomState(seed)
    h, w = ex.host_transform(
        np.zeros((256, 256, 3), np.uint8)).shape[:2]
    for _ in range(n):
        yield rng.randint(0, 255,
                          (ex.batch_size, h, w, 3)).astype(np.uint8)


def measure(ex_f32, ex_int8, videos, n_synthetic: int):
    """Per-input rel-L2 of the int8 lane vs fp32 on identical inputs —
    real corpus videos through the real extract path when given, else
    synthetic batches through the real jitted steps."""
    import jax

    from video_features_tpu.ops.precision import rel_l2
    drifts = []
    if videos:
        for v in videos:
            ref = ex_f32.extract(v)[ex_f32.feature_type]
            fast = ex_int8.extract(v)[ex_int8.feature_type]
            drifts.append({'input': v, 'rel_l2': rel_l2(ref, fast),
                           'max_abs': float(np.abs(ref - fast).max())})
        return drifts
    for i, batch in enumerate(synthetic_batches(ex_f32, n_synthetic)):
        dev = jax.device_put(batch)
        ref = np.asarray(ex_f32._step(ex_f32.params, dev))
        fast = np.asarray(ex_int8._step(ex_int8.params, dev))
        drifts.append({'input': f'synthetic[{i}]',
                       'rel_l2': rel_l2(ref, fast),
                       'max_abs': float(np.abs(ref - fast).max())})
    return drifts


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog='calibrate-int8',
        description='pin a per-family int8 scale table + measured drift '
                    '(ops/quant.py; docs/benchmarks.md precision ladder)')
    parser.add_argument('feature_type',
                        help='an INT8_FEATURES family (resnet/clip/timm)')
    parser.add_argument('--checkpoint-path',
                        help='checkpoint to calibrate; the table lands at '
                             '<ckpt>.int8-scales.npz. Omitted = random '
                             'weights (drift measurement only, no table '
                             'to pin)')
    parser.add_argument('--model-name', help='family model/arch override')
    parser.add_argument('--videos', nargs='*', default=[],
                        help='corpus videos to measure drift over '
                             '(default: synthetic batches)')
    parser.add_argument('--n-synthetic', type=int, default=4,
                        help='synthetic calibration batches when no '
                             'corpus is given (default 4)')
    parser.add_argument('--out', help='scale table path override')
    parser.add_argument('--device', default=None,
                        help='device override (default: config default)')
    args = parser.parse_args(argv)

    from video_features_tpu.ops.precision import (
        INT8_REL_L2_BOUNDS, check_compute_dtype,
    )
    from video_features_tpu.ops.quant import (
        derive_scales, save_scale_table, scale_table_path,
    )
    from video_features_tpu.transplant.torch2jax import _flatten
    # fail exactly like a build would for a refusing family
    check_compute_dtype(args.feature_type, 'int8')

    import tempfile
    tmp_root = tempfile.mkdtemp(prefix='calibrate_int8_')
    overrides = {}
    if args.checkpoint_path:
        overrides['checkpoint_path'] = args.checkpoint_path
    else:
        overrides['allow_random_weights'] = True
    if args.model_name:
        overrides['model_name'] = args.model_name
    if args.device:
        overrides['device'] = args.device

    ex_f32 = build_lane(args.feature_type, 'float32', overrides, tmp_root)
    ex_int8 = build_lane(args.feature_type, 'int8', overrides, tmp_root)

    # the table is derived from the FP32 transplanted layout — exactly
    # what quantize_flat would compute at build (ops/quant._channel_axis
    # decides eligibility in both places)
    import jax
    flat = {k: np.asarray(v) for k, v in
            _flatten(jax.tree_util.tree_map(np.asarray,
                                            ex_f32.params)).items()}
    scales = derive_scales(flat)

    drifts = measure(ex_f32, ex_int8, args.videos, args.n_synthetic)
    worst = max(d['rel_l2'] for d in drifts)
    bound = INT8_REL_L2_BOUNDS[args.feature_type]

    table_path = None
    if args.out or args.checkpoint_path:
        table_path = args.out or scale_table_path(args.checkpoint_path)
        save_scale_table(table_path, scales, meta={
            'feature_type': args.feature_type,
            'measured_rel_l2': f'{worst:.6e}',
            'n_inputs': str(len(drifts)),
            'corpus': ';'.join(args.videos) if args.videos else 'synthetic',
        })

    print(json.dumps({
        'feature_type': args.feature_type,
        'n_scale_tensors': len(scales),
        'scale_table': table_path,
        'drifts': drifts,
        'worst_rel_l2': worst,
        'bound': bound,
        'under_bound': bool(worst <= bound),
    }))
    return 0 if worst <= bound else 1


if __name__ == '__main__':
    raise SystemExit(main())
