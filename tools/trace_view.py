#!/usr/bin/env python3
"""Validate (and summarize) a Chrome trace-event JSON export.

``obs.spans.SpanRecorder.export`` (the ``trace_out=`` knob on all three
execution paths) writes the ``traceEvents`` document this tool checks.
CI runs it against a dryrun-produced trace so a refactor that breaks the
export surfaces as a red test, not as Perfetto silently rendering an
empty timeline a week later.

Checks:
  * top level is an object with a ``traceEvents`` list;
  * every event carries ``name``/``ph``/``ts``/``pid``/``tid`` (ids
    present), ``ts >= 0``; complete events (``X``) carry ``dur >= 0``;
  * begin/end (``B``/``E``) events balance per ``(pid, tid)`` with
    LIFO name matching (the recorder emits ``X`` spans, but hand-made
    or merged traces may not);
  * timestamps are monotonically non-decreasing over the event list
    (the exporter sorts; a torn or hand-concatenated file fails here);
  * every event whose args carry a ``trace_id`` also carries a
    ``span_id`` (the vft-flight pairing contract — an unpaired trace_id
    breaks parent/child reconstruction; batch-level ``trace_ids`` lists
    are exempt, they annotate shared work).

Request tracing (vft-flight): ``--trace-id <id>`` filters the summary
to one request's events, and every trace present gets a critical-path
summary — the longest chain of non-overlapping spans, i.e. the lower
bound on that request's wall time no amount of added parallelism
removes.

Exit codes: 0 valid · 1 invalid (details on stderr) · 2 usage/IO error.

Multiple files merge into ONE timeline before validation and the
critical-path summaries: metadata events first, then every timeline
event ts-sorted, with per-file ``events_dropped`` summed — the
fleet-debugging workflow, where the router export and each backend's
export land in separate files but share trace_ids (vft-scope forwards
one traceparent across hosts, so grouping by trace_id stitches the
request back together). A SINGLE file is still checked as-written —
no re-sort — so a torn export keeps failing the monotonicity check.

Usage:
    python tools/trace_view.py TRACE.json [MORE.json ...]
                               [--quiet] [--trace-id ID]
"""
from __future__ import annotations

import argparse
import json
import sys
from collections import defaultdict
from typing import Any, Dict, List, Tuple

REQUIRED_KEYS = ('name', 'ph', 'ts', 'pid', 'tid')
# metadata events (process/thread naming) are exempt from the timeline
# checks — viewers place them outside the time axis
META_PHASES = ('M',)


def validate_events(events: List[Dict[str, Any]]) -> List[str]:
    """All violations found (empty list = valid)."""
    errors: List[str] = []
    open_stacks: Dict[Tuple[Any, Any], List[str]] = defaultdict(list)
    last_ts = None
    for i, ev in enumerate(events):
        if not isinstance(ev, dict):
            errors.append(f'event[{i}]: not an object')
            continue
        missing = [k for k in REQUIRED_KEYS if k not in ev]
        if missing:
            errors.append(f'event[{i}] ({ev.get("name")!r}): missing '
                          f'keys {missing}')
            continue
        ph = ev['ph']
        if ph in META_PHASES:
            continue
        args = ev.get('args')
        if isinstance(args, dict) and 'trace_id' in args \
                and 'span_id' not in args:
            # the vft-flight pairing contract: a trace-scoped event
            # names its own span too (plural trace_ids — shared batch
            # annotations — are exempt by construction)
            errors.append(f'event[{i}] ({ev["name"]!r}): args carry '
                          f'trace_id without span_id')
        ts = ev['ts']
        if not isinstance(ts, (int, float)) or ts < 0:
            errors.append(f'event[{i}] ({ev["name"]!r}): bad ts {ts!r}')
            continue
        if last_ts is not None and ts < last_ts:
            errors.append(f'event[{i}] ({ev["name"]!r}): ts {ts} < '
                          f'previous {last_ts} (not monotonic)')
        last_ts = ts
        if ph == 'X':
            dur = ev.get('dur')
            if not isinstance(dur, (int, float)) or dur < 0:
                errors.append(f'event[{i}] ({ev["name"]!r}): X event '
                              f'with bad dur {dur!r}')
        elif ph == 'B':
            open_stacks[(ev['pid'], ev['tid'])].append(ev['name'])
        elif ph == 'E':
            stack = open_stacks[(ev['pid'], ev['tid'])]
            if not stack:
                errors.append(f'event[{i}] ({ev["name"]!r}): E without '
                              f'matching B on tid {ev["tid"]}')
            elif stack[-1] != ev['name']:
                errors.append(f'event[{i}]: E {ev["name"]!r} crosses '
                              f'open B {stack[-1]!r}')
            else:
                stack.pop()
    for (pid, tid), stack in open_stacks.items():
        if stack:
            errors.append(f'unclosed B events on pid {pid} tid {tid}: '
                          f'{stack}')
    return errors


def event_trace_ids(ev: Dict[str, Any]) -> List[str]:
    """Every trace id an event is tagged with: its own ``trace_id``
    plus any shared-batch ``trace_ids`` membership."""
    args = ev.get('args') or {}
    ids = []
    if args.get('trace_id'):
        ids.append(args['trace_id'])
    for tid in (args.get('trace_ids') or ()):
        if tid not in ids:
            ids.append(tid)
    return ids


def group_by_trace(events: List[Dict[str, Any]]
                   ) -> Dict[str, List[Dict[str, Any]]]:
    """trace_id → its events (spans AND instants), in list order."""
    groups: Dict[str, List[Dict[str, Any]]] = defaultdict(list)
    for ev in events:
        for tid in event_trace_ids(ev):
            groups[tid].append(ev)
    return groups


def critical_path(spans: List[Dict[str, Any]]
                  ) -> Tuple[float, List[Dict[str, Any]]]:
    """The longest (max total duration) chain of non-overlapping 'X'
    spans — weighted interval scheduling, O(n log n). This is the lower
    bound on the request's wall time that no added parallelism removes:
    everything off the chain already overlapped something on it."""
    from bisect import bisect_right
    iv = sorted(((float(e['ts']),
                  float(e['ts']) + float(e.get('dur', 0.0)), e)
                 for e in spans if e.get('ph') == 'X'),
                key=lambda x: x[1])
    if not iv:
        return 0.0, []
    ends = [t for _, t, _ in iv]
    # best[i] = (total_dur, chain) over the first i intervals
    best: List[Tuple[float, List[Dict[str, Any]]]] = [(0.0, [])]
    for i, (s, t, e) in enumerate(iv):
        j = bisect_right(ends, s, 0, i)     # last interval ending <= s
        take = best[j][0] + (t - s)
        if take > best[i][0]:
            best.append((take, best[j][1] + [e]))
        else:
            best.append(best[i])
    return best[-1]


def trace_summaries(events: List[Dict[str, Any]],
                    only: str = None) -> str:
    """Per-trace critical-path summary lines (all traces, or one)."""
    groups = group_by_trace(events)
    if only is not None:
        groups = {k: v for k, v in groups.items() if k == only}
    if not groups:
        return ''
    lines = []
    for tid in sorted(groups):
        evs = groups[tid]
        spans = [e for e in evs if e.get('ph') == 'X']
        if spans:
            t0 = min(float(e['ts']) for e in spans)
            t1 = max(float(e['ts']) + float(e.get('dur', 0.0))
                     for e in spans)
            wall = t1 - t0
        else:
            wall = 0.0
        cp_total, chain = critical_path(spans)
        share = (cp_total / wall * 100.0) if wall > 0 else 0.0
        lines.append(
            f'trace {tid}: {len(spans)} span(s), wall '
            f'{wall / 1e3:.3f} ms, critical path {cp_total / 1e3:.3f} '
            f'ms ({share:.0f}%)')
        for e in chain:
            lines.append(f'  {e["name"]:<20} @{float(e["ts"]) / 1e3:10.3f}'
                         f' ms  {float(e.get("dur", 0.0)) / 1e3:9.3f} ms')
    return '\n'.join(lines)


def summarize(events: List[Dict[str, Any]]) -> str:
    spans: Dict[str, List[float]] = defaultdict(list)
    instants: Dict[str, int] = defaultdict(int)
    for ev in events:
        if ev.get('ph') == 'X':
            spans[ev['name']].append(float(ev.get('dur', 0.0)))
        elif ev.get('ph') == 'i':
            instants[ev['name']] += 1
    lines = []
    if spans:
        width = max(len(n) for n in spans)
        lines.append(f'{"span".ljust(width)} | count |  total ms |  mean us')
        for name in sorted(spans, key=lambda n: -sum(spans[n])):
            durs = spans[name]
            lines.append(f'{name.ljust(width)} | {len(durs):5d} '
                         f'| {sum(durs) / 1e3:9.3f} '
                         f'| {sum(durs) / len(durs):8.1f}')
    for name in sorted(instants):
        lines.append(f'instant {name}: {instants[name]}')
    return '\n'.join(lines) if lines else '(no timeline events)'


def main(argv: List[str] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument('trace', nargs='+',
                    help='Chrome trace-event JSON file(s); several merge '
                         'into one ts-sorted timeline (events sharing a '
                         'trace_id group across files)')
    ap.add_argument('--quiet', action='store_true',
                    help='validate only; no summary table')
    ap.add_argument('--trace-id', default=None, metavar='ID',
                    help='summarize only the events of one request '
                         'trace (vft-flight trace_id)')
    args = ap.parse_args(argv)

    docs = []
    for path in args.trace:
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            print(f'trace_view: cannot read {path}: {e}', file=sys.stderr)
            return 2
        if not isinstance(doc, dict) or \
                not isinstance(doc.get('traceEvents'), list):
            print(f'trace_view: {path}: not a trace-event document '
                  '(expected an object with a traceEvents list)',
                  file=sys.stderr)
            return 1
        docs.append(doc)

    if len(docs) == 1:
        # single file: check as-written (a torn export must keep failing
        # the monotonicity check), exactly the pre-merge behavior
        events = docs[0]['traceEvents']
        dropped = (docs[0].get('otherData') or {}).get('events_dropped', 0)
    else:
        merged = [ev for doc in docs for ev in doc['traceEvents']]
        # metadata first, then the joint ts-sorted timeline (stable, so
        # equal timestamps keep per-file order) — the same ordering the
        # recorders' own merge uses
        events = sorted(merged,
                        key=lambda e: (isinstance(e, dict)
                                       and e.get('ph') not in META_PHASES,
                                       (e.get('ts', 0)
                                        if isinstance(e, dict) else 0)))
        dropped = sum((doc.get('otherData') or {}).get('events_dropped', 0)
                      for doc in docs)
    errors = validate_events(events)
    if errors:
        for err in errors[:50]:
            print(f'trace_view: {err}', file=sys.stderr)
        print(f'trace_view: INVALID — {len(errors)} violation(s) in '
              f'{len(events)} events', file=sys.stderr)
        return 1
    if args.trace_id is not None:
        selected = [e for e in events
                    if args.trace_id in event_trace_ids(e)]
        if not selected:
            # the document is VALID — the filter just matched nothing;
            # say so on stderr without changing the exit contract
            print(f'trace_view: no events for trace {args.trace_id!r}',
                  file=sys.stderr)
        if not args.quiet:
            print(summarize(selected))
            cp = trace_summaries(selected, only=args.trace_id)
            if cp:
                print(cp)
        print(f'trace_view: OK — {len(selected)}/{len(events)} events '
              f'for trace {args.trace_id}'
              + (f' ({dropped} dropped at record time)' if dropped
                 else ''))
        return 0
    if not args.quiet:
        print(summarize(events))
        cp = trace_summaries(events)
        if cp:
            print(cp)
    print(f'trace_view: OK — {len(events)} events'
          + (f' ({dropped} dropped at record time)' if dropped else ''))
    return 0


if __name__ == '__main__':
    raise SystemExit(main())
