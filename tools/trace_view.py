#!/usr/bin/env python3
"""Validate (and summarize) a Chrome trace-event JSON export.

``obs.spans.SpanRecorder.export`` (the ``trace_out=`` knob on all three
execution paths) writes the ``traceEvents`` document this tool checks.
CI runs it against a dryrun-produced trace so a refactor that breaks the
export surfaces as a red test, not as Perfetto silently rendering an
empty timeline a week later.

Checks:
  * top level is an object with a ``traceEvents`` list;
  * every event carries ``name``/``ph``/``ts``/``pid``/``tid`` (ids
    present), ``ts >= 0``; complete events (``X``) carry ``dur >= 0``;
  * begin/end (``B``/``E``) events balance per ``(pid, tid)`` with
    LIFO name matching (the recorder emits ``X`` spans, but hand-made
    or merged traces may not);
  * timestamps are monotonically non-decreasing over the event list
    (the exporter sorts; a torn or hand-concatenated file fails here).

Exit codes: 0 valid · 1 invalid (details on stderr) · 2 usage/IO error.

Usage:
    python tools/trace_view.py TRACE.json [--quiet]
"""
from __future__ import annotations

import argparse
import json
import sys
from collections import defaultdict
from typing import Any, Dict, List, Tuple

REQUIRED_KEYS = ('name', 'ph', 'ts', 'pid', 'tid')
# metadata events (process/thread naming) are exempt from the timeline
# checks — viewers place them outside the time axis
META_PHASES = ('M',)


def validate_events(events: List[Dict[str, Any]]) -> List[str]:
    """All violations found (empty list = valid)."""
    errors: List[str] = []
    open_stacks: Dict[Tuple[Any, Any], List[str]] = defaultdict(list)
    last_ts = None
    for i, ev in enumerate(events):
        if not isinstance(ev, dict):
            errors.append(f'event[{i}]: not an object')
            continue
        missing = [k for k in REQUIRED_KEYS if k not in ev]
        if missing:
            errors.append(f'event[{i}] ({ev.get("name")!r}): missing '
                          f'keys {missing}')
            continue
        ph = ev['ph']
        if ph in META_PHASES:
            continue
        ts = ev['ts']
        if not isinstance(ts, (int, float)) or ts < 0:
            errors.append(f'event[{i}] ({ev["name"]!r}): bad ts {ts!r}')
            continue
        if last_ts is not None and ts < last_ts:
            errors.append(f'event[{i}] ({ev["name"]!r}): ts {ts} < '
                          f'previous {last_ts} (not monotonic)')
        last_ts = ts
        if ph == 'X':
            dur = ev.get('dur')
            if not isinstance(dur, (int, float)) or dur < 0:
                errors.append(f'event[{i}] ({ev["name"]!r}): X event '
                              f'with bad dur {dur!r}')
        elif ph == 'B':
            open_stacks[(ev['pid'], ev['tid'])].append(ev['name'])
        elif ph == 'E':
            stack = open_stacks[(ev['pid'], ev['tid'])]
            if not stack:
                errors.append(f'event[{i}] ({ev["name"]!r}): E without '
                              f'matching B on tid {ev["tid"]}')
            elif stack[-1] != ev['name']:
                errors.append(f'event[{i}]: E {ev["name"]!r} crosses '
                              f'open B {stack[-1]!r}')
            else:
                stack.pop()
    for (pid, tid), stack in open_stacks.items():
        if stack:
            errors.append(f'unclosed B events on pid {pid} tid {tid}: '
                          f'{stack}')
    return errors


def summarize(events: List[Dict[str, Any]]) -> str:
    spans: Dict[str, List[float]] = defaultdict(list)
    instants: Dict[str, int] = defaultdict(int)
    for ev in events:
        if ev.get('ph') == 'X':
            spans[ev['name']].append(float(ev.get('dur', 0.0)))
        elif ev.get('ph') == 'i':
            instants[ev['name']] += 1
    lines = []
    if spans:
        width = max(len(n) for n in spans)
        lines.append(f'{"span".ljust(width)} | count |  total ms |  mean us')
        for name in sorted(spans, key=lambda n: -sum(spans[n])):
            durs = spans[name]
            lines.append(f'{name.ljust(width)} | {len(durs):5d} '
                         f'| {sum(durs) / 1e3:9.3f} '
                         f'| {sum(durs) / len(durs):8.1f}')
    for name in sorted(instants):
        lines.append(f'instant {name}: {instants[name]}')
    return '\n'.join(lines) if lines else '(no timeline events)'


def main(argv: List[str] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument('trace', help='Chrome trace-event JSON file')
    ap.add_argument('--quiet', action='store_true',
                    help='validate only; no summary table')
    args = ap.parse_args(argv)

    try:
        with open(args.trace) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        print(f'trace_view: cannot read {args.trace}: {e}', file=sys.stderr)
        return 2
    if not isinstance(doc, dict) or \
            not isinstance(doc.get('traceEvents'), list):
        print('trace_view: not a trace-event document (expected an '
              'object with a traceEvents list)', file=sys.stderr)
        return 1

    events = doc['traceEvents']
    errors = validate_events(events)
    if errors:
        for err in errors[:50]:
            print(f'trace_view: {err}', file=sys.stderr)
        print(f'trace_view: INVALID — {len(errors)} violation(s) in '
              f'{len(events)} events', file=sys.stderr)
        return 1
    dropped = (doc.get('otherData') or {}).get('events_dropped', 0)
    if not args.quiet:
        print(summarize(events))
    print(f'trace_view: OK — {len(events)} events'
          + (f' ({dropped} dropped at record time)' if dropped else ''))
    return 0


if __name__ == '__main__':
    raise SystemExit(main())
