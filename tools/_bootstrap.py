"""Shared launcher plumbing for the analysis CLIs (vft-lint /
vft-programs).

Both tools must work from a source checkout without installation, and
both gate CI on the exit-code contract declared once in
``video_features_tpu/analysis/core.py`` (EXIT_CLEAN / EXIT_ERROR /
EXIT_FINDINGS / EXIT_IMPURE). This module holds the one copy of the
repo-root resolution so the two wrappers cannot drift.

Import-order note: :func:`add_repo_root` only touches ``sys.path`` — it
deliberately imports nothing from the package, because vft_lint.py must
snapshot ``sys.modules`` (its jax-purity probe) and vft_programs.py must
pin the jax platform env BEFORE the first package import.
"""
import sys
from pathlib import Path


def repo_root() -> Path:
    return Path(__file__).resolve().parent.parent


def add_repo_root() -> Path:
    """Prepend the repo root to ``sys.path`` (idempotent) so the package
    resolves from a source checkout; returns the root."""
    root = repo_root()
    if str(root) not in sys.path:
        sys.path.insert(0, str(root))
    return root
