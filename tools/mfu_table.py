#!/usr/bin/env python3
"""MFU accounting: FLOPs/clip → achieved TFLOP/s → % of v5e bf16 peak.

VERDICT r4 weak-point 4: rates like "289 clips/s" are unanchored without
a FLOP denominator — good, or 10× off peak? This tool computes, for
every BASELINE family plus the fused i3d step at BOTH geometries:

  * FLOPs per work unit from XLA's own ``compile().cost_analysis()`` of
    the production step (the same jitted fn the extractor calls). XLA
    counts multiply+add as 2 FLOPs, so resnet50@224 reports ~8.0 G —
    the canonical number.
  * the measured in-graph rate (bench.py's shared scan harness, fresh).
  * achieved TFLOP/s = FLOPs/unit × rate, and % of the v5e chip's dense
    bf16 peak (197 TFLOP/s, the public spec).

Precision caveat printed with the table: at ``mixed`` (3-pass bf16)
every matmul EXECUTES ~3× its nominal FLOPs, so hardware occupancy on
matmul-dominated graphs is ≈3× the quoted model-FLOPs utilization —
MFU here is deliberately model-FLOPs-based (the useful-work number),
matching how the scaling literature quotes it.

    python tools/mfu_table.py                 # real TPU, full table
    BENCH_PLATFORM=cpu python tools/mfu_table.py s3d   # smoke, one family

Prints one JSON line per row (family, unit, gflops_per_unit, rate,
achieved_tflops, mfu_pct) then a markdown table on stderr for docs.
"""
from __future__ import annotations

import json
import os
import sys
from functools import partial
from pathlib import Path

import numpy as np

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

V5E_BF16_PEAK_TFLOPS = 197.0   # dense bf16, public v5e spec


def _flops_of(jitted_lowered) -> float:
    comp = jitted_lowered.compile()
    ca = comp.cost_analysis()
    if isinstance(ca, list):
        ca = ca[0]
    return float(ca.get('flops', float('nan')))


def fused_i3d_row(jax, ambient, pins, device, platform, h, w, batch,
                  label):
    """(label, 'clips', flops_per_clip, rate) for the fused two-stream
    step at one geometry — rate via bench.py's bench_ingraph harness."""
    from bench import bench_ingraph
    from video_features_tpu.extract.i3d import fused_two_stream_step
    from video_features_tpu.models import i3d as i3d_model
    from video_features_tpu.models import raft as raft_model
    from video_features_tpu.transplant.torch2jax import transplant

    params = jax.device_put({
        'rgb': transplant(i3d_model.init_state_dict(modality='rgb')),
        'flow': transplant(i3d_model.init_state_dict(modality='flow')),
        'raft': transplant(raft_model.init_state_dict()),
    }, device)
    stack = int(os.environ.get('BENCH_STACK', 16))
    pads = tuple(raft_model.pad_to_multiple(
        np.zeros((1, h, w, 1), np.float32))[1])

    def step(p, stacks):
        with jax.default_matmul_precision(ambient):
            return fused_two_stream_step(
                p, stacks, pads=pads, streams=('rgb', 'flow'),
                crop_size=min(224, h, w), platform=platform, pins=pins)

    x = np.zeros((batch, stack + 1, h, w, 3), np.float32)
    flops = _flops_of(jax.jit(step).lower(params, x)) / batch
    iters = int(os.environ.get('BENCH_ITERS', 4))
    rate = bench_ingraph(jax, ambient, pins, device, platform, params,
                         stack, h, w, batch, iters)
    return label, 'clips', flops, rate


def family_rows(jax, ambient, device, on_accel, picks):
    """picks: None → every family; a list (possibly empty) → exactly
    those families (so `mfu_table.py i3d` runs NO family rows, not all)."""
    from bench import bench_family_ingraph
    from tools.family_precision_study import _family_specs
    from video_features_tpu.transplant.torch2jax import transplant

    iters = int(os.environ.get('BENCH_ITERS', 4))
    for fam, (init_fn, step_fn, bshape, unit, imap,
              count) in _family_specs(on_accel).items():
        if picks is not None and fam not in picks:
            continue
        params = jax.device_put(transplant(init_fn()), device)

        def step(p, x):
            with jax.default_matmul_precision(ambient):
                return step_fn(p, x)

        x = np.zeros(bshape, np.float32)
        n_units = count if count is not None else bshape[0]
        flops = _flops_of(jax.jit(step).lower(params, x)) / n_units
        rate = bench_family_ingraph(jax, ambient, device, init_fn,
                                    step_fn, bshape, imap, count, iters,
                                    transplant)
        yield fam, unit.split('/')[0], flops, rate


def main() -> int:
    import jax
    if os.environ.get('BENCH_PLATFORM'):
        jax.config.update('jax_platforms', os.environ['BENCH_PLATFORM'])
    from video_features_tpu.ops.precision import MIXED_AMBIENT, MIXED_PINS
    from video_features_tpu.utils.device import (
        enable_compilation_cache, jax_device,
    )

    platform = jax.devices()[0].platform
    on_accel = platform != 'cpu'
    enable_compilation_cache('~/.cache/video_features_tpu/xla', platform)
    device = jax_device(platform)
    precision = os.environ.get('BENCH_PRECISION', 'mixed')
    ambient, pins = ((MIXED_AMBIENT, MIXED_PINS) if precision == 'mixed'
                     else (precision, None))
    picks = sys.argv[1:]

    rows = []
    if not picks or 'i3d' in picks:
        h, w = (256, 340) if on_accel else (64, 86)
        batch = 16 if on_accel else 1
        rows.append(fused_i3d_row(jax, ambient, pins, device, platform,
                                  h, w, batch, f'i3d_fused_{h}x{w}'))
        if on_accel:
            rows.append(fused_i3d_row(jax, ambient, pins, device,
                                      platform, 224, 224, batch,
                                      'i3d_fused_224px'))
    rows.extend(family_rows(
        jax, ambient, device, on_accel,
        None if not picks else [p for p in picks if p != 'i3d']))

    md = ['| step | GFLOPs/unit | measured rate | achieved TFLOP/s | '
          '% of v5e bf16 peak |', '|---|---|---|---|---|']
    for label, unit, flops, rate in rows:
        tflops = flops * rate / 1e12
        mfu = tflops / V5E_BF16_PEAK_TFLOPS * 100
        print(json.dumps({
            'step': label, 'unit': unit, 'precision': precision,
            'gflops_per_unit': round(flops / 1e9, 2),
            'rate': round(rate, 2),
            'achieved_tflops': round(tflops, 2),
            'mfu_pct_v5e_bf16': round(mfu, 2),
        }), flush=True)
        md.append(f'| {label} | {flops / 1e9:.1f} | {rate:.1f} {unit}/s '
                  f'| {tflops:.1f} | {mfu:.1f}% |')
    print('\n'.join(md), file=sys.stderr)
    return 0


if __name__ == '__main__':
    raise SystemExit(main())
