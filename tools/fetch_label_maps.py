#!/usr/bin/env python3
"""REFRESH the K400/IN1K/IN21K label-map files for ``show_pred``.

The three maps already ship as package data
(`video_features_tpu/utils/label_maps/`), so class names work out of the
box; this tool only REGENERATES them (e.g. to track an upstream rename)
into a directory exported as ``$VFT_LABEL_MAP_DIR``, which takes
precedence over the bundled copies. It materializes from whatever source
is available, in priority order:

  1. torchvision weight metadata (Kinetics-400 from the r2plus1d weights,
     ImageNet-1k from the resnet50 weights) — requires `torchvision`;
  2. timm's dataset info (`imagenet-21k`) — requires `timm`;
  3. an existing `video_features` checkout (``--from-checkout PATH``), whose
     `utils/*_label_map.txt` files are copied as-is.

Usage:
    python tools/fetch_label_maps.py --out ./label_maps \
        [--from-checkout /path/to/video_features]
    export VFT_LABEL_MAP_DIR=./label_maps
"""
from __future__ import annotations

import argparse
import shutil
import sys
from pathlib import Path

FILES = {
    'kinetics': 'K400_label_map.txt',
    'imagenet1k': 'IN1K_label_map.txt',
    'imagenet21k': 'IN21K_label_map.txt',
}


def from_torchvision(out: Path) -> list:
    written = []
    try:
        from torchvision.models import ResNet50_Weights
        from torchvision.models.video import R2Plus1D_18_Weights
    except ImportError:
        return written
    for weights, key in ((R2Plus1D_18_Weights.DEFAULT, 'kinetics'),
                         (ResNet50_Weights.IMAGENET1K_V1, 'imagenet1k')):
        cats = weights.meta.get('categories')
        if cats:
            (out / FILES[key]).write_text('\n'.join(cats) + '\n')
            written.append(key)
    return written


def from_timm(out: Path) -> list:
    try:
        from timm.data import ImageNetInfo
    except ImportError:
        return []
    try:
        info = ImageNetInfo('imagenet-21k')
        names = [info.index_to_description(i)
                 for i in range(info.num_classes())]
    except Exception:
        return []
    (out / FILES['imagenet21k']).write_text('\n'.join(names) + '\n')
    return ['imagenet21k']


def from_checkout(out: Path, checkout: Path) -> list:
    written = []
    for key, fname in FILES.items():
        src = checkout / 'utils' / fname
        if src.exists():
            shutil.copy(src, out / fname)
            written.append(key)
    return written


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument('--out', required=True, help='output directory')
    ap.add_argument('--from-checkout', default=None,
                    help='path to a video_features checkout to copy from')
    ns = ap.parse_args()

    out = Path(ns.out)
    out.mkdir(parents=True, exist_ok=True)
    done: set = set()
    done.update(from_torchvision(out))
    if 'imagenet21k' not in done:
        done.update(from_timm(out))
    missing = set(FILES) - done
    if missing and ns.from_checkout:
        done.update(from_checkout(out, Path(ns.from_checkout)))
        missing = set(FILES) - done

    for key in sorted(done):
        print(f'wrote {out / FILES[key]}')
    for key in sorted(missing):
        print(f'MISSING {key} ({FILES[key]}): no source available '
              '(install torchvision/timm or pass --from-checkout)',
              file=sys.stderr)
    return 0 if done else 1


if __name__ == '__main__':
    raise SystemExit(main())
