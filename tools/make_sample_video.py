#!/usr/bin/env python3
"""Generate synthetic sample media for demos/tests (no downloads needed).

The reference ships sample .mp4 clips; this repo generates equivalents on
demand: a moving-pattern video (exercises decode, resize, optical flow —
the pattern translates at a known velocity, so RAFT output is visually
checkable) and a tone .wav for the vggish path. Also writes
``sample_video_paths.txt`` in the output directory (the
``file_with_video_paths`` input format: one path per line).

Usage:
    python tools/make_sample_video.py --out ./sample \
        [--seconds 4] [--fps 25] [--size 320x240]
"""
from __future__ import annotations

import argparse
import wave
from pathlib import Path

import numpy as np


def write_noise_clip(path, n_frames: int, w: int = 64, h: int = 48,
                     seed: int = 0) -> str:
    """A deterministic little mp4: a noise card scrolling horizontally.

    The ONE tiny-fixture clip writer shared by the packing/serve test
    suites and the driver's ``dryrun_serve`` — a codec/fps tweak here
    reaches every consumer at once.
    """
    import cv2

    wr = cv2.VideoWriter(str(path), cv2.VideoWriter_fourcc(*'mp4v'),
                         25.0, (w, h))
    rng = np.random.RandomState(seed)
    base = (rng.rand(h, w, 3) * 255).astype(np.uint8)
    for t in range(n_frames):
        wr.write(np.roll(base, t * 3, axis=1))
    wr.release()
    return str(path)


def write_video(path: Path, seconds: float, fps: float, w: int, h: int) -> None:
    import cv2

    rng = np.random.RandomState(0)
    # random blobs on a gradient background; the whole field translates at
    # (2, 1) px/frame so flow ≈ constant and visually verifiable
    base_h, base_w = h * 2, w * 2
    yy, xx = np.mgrid[0:base_h, 0:base_w]
    base = ((xx * 255 / base_w + yy * 128 / base_h) % 255).astype(np.uint8)
    base = np.stack([base, np.roll(base, 37, 0), np.roll(base, 91, 1)], -1)
    for _ in range(40):
        cy, cx = rng.randint(0, base_h), rng.randint(0, base_w)
        r = rng.randint(8, 32)
        color = rng.randint(0, 255, 3).tolist()
        cv2.circle(base, (cx, cy), r, color, -1)

    writer = cv2.VideoWriter(str(path), cv2.VideoWriter_fourcc(*'mp4v'),
                             fps, (w, h))
    n = int(seconds * fps)
    for t in range(n):
        dy, dx = (t * 1) % h, (t * 2) % w
        frame = np.roll(np.roll(base, -dy, 0), -dx, 1)[:h, :w]
        writer.write(frame)
    writer.release()


def write_tone(path: Path, seconds: float = 3.0, sr: int = 16000,
               freq: float = 440.0) -> None:
    t = np.arange(int(sr * seconds)) / sr
    samples = (np.sin(2 * np.pi * freq * t) * 0.5 * 32767).astype('<i2')
    with wave.open(str(path), 'wb') as f:
        f.setnchannels(1)
        f.setsampwidth(2)
        f.setframerate(sr)
        f.writeframes(samples.tobytes())


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument('--out', required=True)
    ap.add_argument('--seconds', type=float, default=4.0)
    ap.add_argument('--fps', type=float, default=25.0)
    ap.add_argument('--size', default='320x240')
    ns = ap.parse_args()

    out = Path(ns.out)
    out.mkdir(parents=True, exist_ok=True)
    w, h = (int(v) for v in ns.size.split('x'))

    video = out / 'sample_moving_pattern.mp4'
    tone = out / 'sample_tone.wav'
    write_video(video, ns.seconds, ns.fps, w, h)
    write_tone(tone)
    (out / 'sample_video_paths.txt').write_text(f'{video.resolve()}\n')
    print(f'wrote {video}\nwrote {tone}\nwrote {out / "sample_video_paths.txt"}')
    return 0


if __name__ == '__main__':
    raise SystemExit(main())
