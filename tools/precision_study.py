#!/usr/bin/env python3
"""Measure drift vs speed for mixed-precision policies on the fused path.

The parity bar (rel L2 ≤ 1e-3 vs the reference) pins global matmul
precision to 'highest' — bf16 MXU passes drift 1.3e-2 end-to-end because
the flow uint8 quantization cliff amplifies flow error. This tool sweeps
per-sub-graph policies (ops/precision.py pins) on real hardware and prints
one JSON line per policy: drift vs the all-highest baseline (same inputs,
same weights) and in-graph clips/sec — the data behind the 'mixed'
precision mode's pin set (ops/precision.py:MIXED_PINS).

On TPU, matmul precision maps to bf16 pass counts: default=1 pass,
high=3 (error ~2^-21), highest=6 (~fp32). Timing methodology = bench.py's
(in-graph lax.scan + value fetch; dispatch-timing on the axon remote
backend is fiction).

    python tools/precision_study.py            # sweep on the default device
    BENCH_PLATFORM=cpu python tools/precision_study.py  # smoke (no drift)
"""
from __future__ import annotations

import json
import os
import sys
import time
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

# (name, ambient, pins) — pins may both up-pin (sensitive subgraphs to
# highest) and down-pin (tolerant subgraphs to fast passes).
#
# Round-1 sweep (v5e, batch 8, stack 16, 224px, vs all_highest):
#   all_highest       flow 0        rgb 0        14.6 clips/s
#   all_high          flow 8.4e-04  rgb 1.3e-04  24.2
#   all_default       flow 1.24e-02 rgb 4.1e-03  45.9
#   enc_default       flow 1.04e-02 rgb 0        12.6   (ambient highest)
#   enc_corr_default  flow 1.03e-02 rgb 0        15.9
#   enc_corr_high     flow 6.6e-04  rgb 0        15.5
#   mixed(enc dflt)   flow 1.03e-02 rgb 0        15.9
# ⇒ the fnet/cnet encoders dominate the drift (1-pass bf16 there is 1e-2 on
#   its own); corr tolerates 1-pass; iter+i3d at 1-pass add ~7e-3. So every
#   matmul-heavy subgraph except corr/upsample needs ≥ 'high' (3-pass).
# Round-2 refinement sweep results (drift deterministic; timings on this
# tunnel are load-noisy — calibrate with bench.py):
#   high_corr_default          flow 4.4e-03  (corr needs ≥ high too)
#   high_iter_default          flow 1.3e-02  (iter needs ≥ high)
#   high_i3d_default           flow 3.4e-03 rgb 4.1e-03 (i3d needs ≥ high)
# ⇒ 'mixed' = plain ambient 'high' (8.4e-4), no sub-graph survives 1-pass
#   steady-state. The early-iteration hypothesis (first n refinement
#   iterations at 1-pass, healed by later full-precision ones) was also
#   measured and REJECTED: high_early8_default → flow 1.30e-2 — the GRU
#   hidden state carries the early error through every later iteration.
#   Further parity-precision speed must come from kernels, not precision.
# Round-3 finer-grain sweep (v5e, after the GRU restructure + quantizer
# offset fix; drift deterministic, timings tunnel-noisy):
#   all_high                   flow 8.50e-04  (mixed drift unchanged)
#   high_motion_default        flow 1.08e-02  ✗
#   high_head_default          flow 7.81e-03  ✗
#   high_gru_default           flow 1.00e-02  ✗
#   high_motion_head_default   flow 1.11e-02  ✗
# ⇒ the 1-pass intolerance holds at PER-CONV granularity inside the
#   refinement iteration: every component's output feeds back through the
#   coords→lookup loop within one iteration, so there is no "cold side" to
#   down-pin. The precision lever is exhausted at every measured
#   granularity (docs/benchmarks.md has the consolidated analysis).
POLICIES = [
    ('all_highest', 'highest', None),                       # baseline
    ('all_high', 'high', None),                             # = 'mixed'
    ('high_early8_default', 'high', (('iter_early', 'default:8'),)),
    # Round-3 finer-grain sweep: per-component pins INSIDE the refinement
    # iteration (models/raft.py nests iter_motion/iter_gru/iter_head in
    # 'iter'), probing whether part of the per-iteration conv stack
    # tolerates 1-pass while the GRU feedback path stays 3-pass.
    ('high_motion_default', 'high', (('iter_motion', 'default'),)),
    ('high_head_default', 'high', (('iter_head', 'default'),)),
    ('high_gru_default', 'high', (('iter_gru', 'default'),)),
    ('high_motion_head_default', 'high',
     (('iter_motion', 'default'), ('iter_head', 'default'))),
]


def main() -> None:
    import jax
    import jax.numpy as jnp
    from jax import lax

    if os.environ.get('BENCH_PLATFORM'):
        jax.config.update('jax_platforms', os.environ['BENCH_PLATFORM'])

    from video_features_tpu.extract.i3d import fused_two_stream_step
    from video_features_tpu.models import i3d as i3d_model
    from video_features_tpu.models import raft as raft_model
    from video_features_tpu.transplant.torch2jax import transplant
    from video_features_tpu.utils.device import (
        enable_compilation_cache, jax_device,
    )

    platform = jax.devices()[0].platform
    on_accel = platform != 'cpu'
    stack = int(os.environ.get('BENCH_STACK', 16))
    size = int(os.environ.get('BENCH_SIZE', 224 if on_accel else 64))
    batch = int(os.environ.get('BENCH_BATCH', 8 if on_accel else 1))
    iters = int(os.environ.get('BENCH_ITERS', 4 if on_accel else 1))
    enable_compilation_cache('~/.cache/video_features_tpu/xla', platform)

    device = jax_device(platform)
    params = jax.device_put({
        'rgb': transplant(i3d_model.init_state_dict(modality='rgb')),
        'flow': transplant(i3d_model.init_state_dict(modality='flow')),
        'raft': transplant(raft_model.init_state_dict()),
    }, device)
    rng = np.random.RandomState(0)
    # smooth-ish frames (video-like): white noise makes flow meaningless and
    # understates the quantization-cliff amplification
    base = rng.rand(batch, 1, size // 4, size // 4, 3) * 255
    drift_field = rng.rand(batch, stack + 1, size // 4, size // 4, 3) * 40
    frames = np.clip(base + drift_field, 0, 255).astype(np.float32)
    frames = np.kron(frames, np.ones((1, 1, 4, 4, 1), np.float32))  # upsample
    stacks = jax.device_put(frames, device)
    kwargs = dict(pads=(0, 0, 0, 0), streams=('rgb', 'flow'),
                  crop_size=min(224, size), platform=platform)

    def build(ambient, pins):
        def feats(p, x):
            with jax.default_matmul_precision(ambient):
                return fused_two_stream_step(p, x, pins=pins, **kwargs)

        def timed(p, x):
            def body(carry, _):
                o = feats(p, x)
                return {k: carry[k] + o[k].sum() for k in carry}, None
            acc, _ = lax.scan(
                body, {k: jnp.float32(0) for k in kwargs['streams']},
                None, length=iters)
            return acc
        return jax.jit(feats), jax.jit(timed)

    # CPU executes everything in fp32 regardless of the requested matmul
    # precision — drift is identically 0 and the sweep is meaningless, so
    # smoke-run only the baseline + one pinned policy for plumbing coverage.
    policies = POLICIES if on_accel else [POLICIES[0], POLICIES[-2]]

    results = {}
    for name, ambient, pins in policies:
        # the axon remote-compile tunnel flakes on long sweeps; retry each
        # policy once and keep going — drift numbers are deterministic, a
        # lost policy can rerun later
        for attempt in (1, 2):
            try:
                feats_fn, timed_fn = build(ambient, pins)
                out = jax.tree_util.tree_map(np.asarray,
                                             feats_fn(params, stacks))
                timed_fn(params, stacks)  # compile + warm
                t0 = time.perf_counter()
                acc = jax.tree_util.tree_map(float, timed_fn(params, stacks))
                dt = time.perf_counter() - t0
                break
            except Exception as e:
                print(json.dumps({'policy': name, 'attempt': attempt,
                                  'error': f'{type(e).__name__}: {e}'}),
                      flush=True)
                if attempt == 2 and name == 'all_highest':
                    raise  # no baseline → no drift numbers at all
        else:
            continue
        assert all(np.isfinite(v) for v in acc.values()), (name, acc)
        clips = batch * iters / dt
        if name == 'all_highest':
            results['baseline'] = out
        ref = results['baseline']
        rel = {
            s: float(np.linalg.norm(out[s] - ref[s])
                     / max(np.linalg.norm(ref[s]), 1e-12))
            for s in out
        }
        print(json.dumps({
            'policy': name, 'ambient': ambient,
            'pins': list(map(list, pins)) if pins else [],
            'rel_l2_vs_highest': rel,
            'clips_per_sec': round(clips, 2),
        }), flush=True)


if __name__ == '__main__':
    main()
