"""Reverse-engineer cv2's exact YUV→RGB conversion and emit C tables.

The reference decodes video through ``cv2.VideoCapture`` (reference
utils/io.py:96-154). cv2 ≥5.0 bundles FFmpeg 8's rewritten swscale
(9.5.x), whose yuv420p→RGB integer arithmetic differs from the system
libswscale (6.x) by ~1 level on most pixels — measured in round 4 as a
2.9e-3 feature-level drift through the flow-quantization cliff, which is
why the native decode backend could not be the default.

Rather than approximating, this tool treats cv2 as an oracle and
recovers its conversion EXACTLY:

1. Decode the same videos twice — raw yuv420p planes through our native
   service (``vf_read_yuv``) and RGB through ``cv2.VideoCapture`` — over
   the reference samples plus synthetic full-gamut content (uniform and
   beta-distributed RGB noise, saturated bars, gradients) written with
   ``cv2.VideoWriter``.
2. Verify the map is POINTWISE (no dithering: every (Y,U,V) triple maps
   to one RGB everywhere it occurs, including across the 2×2 chroma
   block — which also proves nearest-neighbor chroma upsampling).
3. Solve the per-channel table decomposition
       R = clip(TY_R[Y] + TV_R[V])
       G = clip(TY_G[Y] + TU_G[U] + TV_G[V])
       B = clip(TY_B[Y] + TU_B[U] + TV_B[B])
   by sparse least squares over unclipped observations. The solve is
   exact (residual ~1e-9) and the entries are integers — cv2's pipeline
   IS table arithmetic. Slopes recovered: Y 9539>>13 (=1.16443, the
   BT.601 limited-range 255/219), R/V 6537>>12, B/U 4131>>11,
   G/U -401>>10, G/V -1665>>11.
4. Entries never observed unclipped (a handful outside the legal
   chroma range) are filled by linear extrapolation, then nudged to
   satisfy every clipped observation (clip(pred)==0/255 inequalities).
5. Verify ZERO mismatches over every collected observation (~1.8M
   unique triples in the round-5 run), then emit
   ``native/yuv2rgb_cv2_tables.h``.

Scope: the tables reproduce cv2's conversion for 8-bit yuv420p with
unspecified/limited color range — the only format the reference corpus
and every H.264 CLI encode here produces. vfdecode.cc uses them for
exactly that case and falls back to swscale otherwise.

Usage:
    python tools/fit_cv2_yuv_tables.py [--videos a.mp4 b.mp4 ...]
                                       [--out native/yuv2rgb_cv2_tables.h]
                                       [--skip-synthetic]
"""
from __future__ import annotations

import argparse
import ctypes
import glob
import os
import sys
import tempfile
from pathlib import Path

import numpy as np

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))


def _bind_read_yuv(lib):
    lib.vf_read_yuv.restype = ctypes.c_long
    lib.vf_read_yuv.argtypes = [ctypes.c_void_p] + [ctypes.c_void_p] * 3


def write_synthetic(tmpdir: str) -> list:
    """Full-gamut synthetic videos via cv2.VideoWriter (mp4v): uniform
    noise, extreme-biased beta noise, 16px blocks (survive 4:2:0+DCT →
    extreme chroma), saturated bars, gradients."""
    import cv2
    rng = np.random.RandomState(0)
    W, H = 320, 240
    out = []

    def emit(name, frames):
        path = os.path.join(tmpdir, name)
        wr = cv2.VideoWriter(path, cv2.VideoWriter_fourcc(*'mp4v'), 30,
                             (frames[0].shape[1], frames[0].shape[0]))
        for f in frames:
            wr.write(f)
        wr.release()
        out.append(path)

    emit('noise.mp4', [rng.randint(0, 256, (H, W, 3), np.uint8)
                       for _ in range(12)])
    emit('beta.mp4', [(255 * rng.beta(0.25, 0.25, (H, W, 3))).astype(np.uint8)
                      for _ in range(40)])
    blocks = []
    for _ in range(40):
        small = (255 * rng.beta(0.2, 0.2, (H // 16, W // 16, 3))).astype(np.uint8)
        blocks.append(np.repeat(np.repeat(small, 16, 0), 16, 1))
    emit('blocks.mp4', blocks)
    cols = [(0, 0, 255), (0, 255, 0), (255, 0, 0), (0, 255, 255),
            (255, 255, 0), (255, 0, 255), (255, 255, 255), (0, 0, 0)]
    bars = []
    for i in range(8):
        f = np.zeros((H, W, 3), np.uint8)
        for j, c in enumerate(cols):
            f[:, j * W // len(cols):(j + 1) * W // len(cols)] = c
        bars.append(np.roll(f, i * 7, axis=1))
    emit('bars.mp4', bars)
    # odd-alignment width exercises any width-dependent SIMD path
    W2 = 326
    emit('noise_oddw.mp4', [rng.randint(0, 256, (H, W2, 3), np.uint8)
                            for _ in range(8)])
    return out


def collect(videos: list, max_frames: int = 40):
    """(Y,V,R), (Y,U,B), (Y,U,V,G) observation arrays, deduplicated, and
    the pointwise-consistency violation count (must be 0)."""
    import cv2
    from video_features_tpu.io.native import load_library
    lib = load_library()
    assert lib is not None, 'native decode library unavailable'
    _bind_read_yuv(lib)

    obsR, obsB, obsG = [], [], []
    for path in videos:
        h0 = lib.vf_open(os.fsencode(path))
        if not h0:
            print(f'  skip (native open failed): {path}', file=sys.stderr)
            continue
        fps = ctypes.c_double(); n = ctypes.c_long()
        w = ctypes.c_int(); h = ctypes.c_int()
        lib.vf_props(h0, ctypes.byref(fps), ctypes.byref(n),
                     ctypes.byref(w), ctypes.byref(h))
        W, H = w.value, h.value
        if W % 2 or H % 2:
            lib.vf_close(h0)
            continue
        cap = cv2.VideoCapture(path)
        Y = np.empty((H, W), np.uint8)
        U = np.empty((H // 2, W // 2), np.uint8)
        V = np.empty((H // 2, W // 2), np.uint8)
        fi = 0
        while fi < max_frames:
            r = lib.vf_read_yuv(h0, Y.ctypes.data, U.ctypes.data,
                                V.ctypes.data)
            ok, bgr = cap.read()
            if r != 1 or not ok:
                break
            rgb = bgr[:, :, ::-1]
            Yb = Y.reshape(H // 2, 2, W // 2, 2).astype(np.int64)
            Rb = rgb.reshape(H // 2, 2, W // 2, 2, 3).astype(np.int64)
            Ue = np.broadcast_to(U[:, None, :, None].astype(np.int64), Yb.shape)
            Ve = np.broadcast_to(V[:, None, :, None].astype(np.int64), Yb.shape)
            obsR.append(np.stack([Yb.ravel(), Ve.ravel(), Rb[..., 0].ravel()], 1))
            obsB.append(np.stack([Yb.ravel(), Ue.ravel(), Rb[..., 2].ravel()], 1))
            obsG.append(np.stack([Yb.ravel(), Ue.ravel(), Ve.ravel(),
                                  Rb[..., 1].ravel()], 1))
            fi += 1
        lib.vf_close(h0)
        cap.release()
        print(f'  {fi} frames from {path}', file=sys.stderr)

    def dedup(obs, nkey, check_consistency=True):
        o = np.concatenate(obs)
        key = np.zeros(len(o), np.int64)
        for i in range(nkey):
            key = (key << 8) | o[:, i]
        order = np.argsort(key, kind='stable')
        ks, vs = key[order], o[order, nkey]
        uniq, start = np.unique(ks, return_index=True)
        # pointwise check: within each group all outputs identical
        bad = 0
        if check_consistency:
            grp_max = np.maximum.reduceat(vs, start)
            grp_min = np.minimum.reduceat(vs, start)
            bad = int((grp_max != grp_min).sum())
        return o[order][start], bad

    R, badR = dedup(obsR, 2)
    B, badB = dedup(obsB, 2)
    G, badG = dedup(obsG, 3)
    assert badR == badB == badG == 0, (
        f'cv2 conversion is NOT pointwise: {badR}/{badB}/{badG} '
        'inconsistent triples — table model invalid')
    return R, B, G


def solve_tables(obs, nterm, lab):
    """Exact integer tables for one channel by sparse lsq over unclipped
    observations + extrapolation/repair for unpinned entries."""
    import scipy.sparse as sp
    from scipy.sparse.linalg import lsqr

    cols = [obs[:, i] for i in range(nterm)]
    out = obs[:, nterm]
    m = (out > 0) & (out < 255)
    rows = np.arange(m.sum())
    ci = np.concatenate([cols[i][m] + 256 * i for i in range(nterm)])
    M = sp.coo_matrix((np.ones(nterm * m.sum()), (np.tile(rows, nterm), ci)),
                      shape=(m.sum(), 256 * nterm)).tocsr()
    sol = lsqr(M, out[m].astype(np.float64), atol=1e-13, btol=1e-13,
               iter_lim=15000)[0]
    resid = np.abs(M @ sol - out[m]).max()
    assert resid < 1e-6, f'{lab}: not separable (resid {resid})'
    tabs = [sol[256 * i:256 * (i + 1)].copy() for i in range(nterm)]
    for i in range(1, nterm):   # gauge: integerize at the best-pinned entry
        pin = np.bincount(cols[i][m], minlength=256).argmax()
        sh = tabs[i][pin] - np.round(tabs[i][pin])
        tabs[i] -= sh
        tabs[0] += sh
    pinned = [np.unique(cols[i][m]) for i in range(nterm)]
    intd = max(np.abs(t[p] - np.round(t[p])).max()
               for t, p in zip(tabs, pinned))
    assert intd < 1e-4, f'{lab}: non-integer table entries ({intd})'
    T = [np.full(256, np.nan) for _ in range(nterm)]
    for i in range(nterm):
        T[i][pinned[i]] = np.round(tabs[i][pinned[i]])
    for t in T:   # unpinned entries: linear extrapolation first
        idx = np.where(~np.isnan(t))[0]
        miss = np.where(np.isnan(t))[0]
        if len(miss):
            t[miss] = np.round(np.polyval(np.polyfit(idx, t[idx], 1), miss))
    T = [t.astype(np.int64) for t in T]
    # repair: nudge unpinned entries until every CLIPPED observation holds
    pinset = [set(p.tolist()) for p in pinned]
    for _ in range(200):
        pred = np.clip(sum(T[i][cols[i]] for i in range(nterm)), 0, 255)
        bad = np.where(pred != out)[0]
        if not len(bad):
            break
        i0 = bad[0]
        for i in range(nterm):
            c = cols[i][i0]
            if c not in pinset[i]:
                T[i][c] += np.sign(int(out[i0]) - int(pred[i0]))
                break
        else:
            raise AssertionError(
                f'{lab}: mismatch at fully pinned entry '
                f'{[int(cols[i][i0]) for i in range(nterm)]}')
    pred = np.clip(sum(T[i][cols[i]] for i in range(nterm)), 0, 255)
    nbad = int((pred != out).sum())
    print(f'{lab}: {len(obs)} unique obs, {nbad} mismatches, '
          f'{[len(p) for p in pinned]} pinned', file=sys.stderr)
    assert nbad == 0, f'{lab}: {nbad} mismatches remain'
    return T


def emit_header(tables: dict, out_path: str, n_obs: int) -> None:
    import cv2
    lines = [
        '// GENERATED by tools/fit_cv2_yuv_tables.py — do not edit.',
        f'// FITTED_CV2_VERSION: {cv2.__version__}',
        '//',
        '// Exact integer tables reproducing cv2 (bundled FFmpeg/swscale)',
        '// yuv420p -> RGB conversion, verified bit-exact over '
        f'{n_obs} unique',
        '// (Y,U,V) observations across the reference samples and synthetic',
        '// full-gamut content. See the tool docstring for the method.',
        '//',
        '//   R = clip(TY_R[Y] + TV_R[V])',
        '//   G = clip(TY_G[Y] + TU_G[U] + TV_G[V])',
        '//   B = clip(TY_B[Y] + TU_B[U])',
        '// chroma: nearest (U,V at [y/2][x/2]); 8-bit limited/unspec range.',
        '#pragma once',
        '#include <cstdint>',
        '',
    ]
    for name, t in tables.items():
        vals = ', '.join(str(int(v)) for v in t)
        lines.append(f'static const int16_t {name}[256] = {{{vals}}};')
    lines.append('')
    Path(out_path).write_text('\n'.join(lines))
    print(f'wrote {out_path}', file=sys.stderr)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument('--videos', nargs='*', default=None)
    ap.add_argument('--out', default=str(REPO / 'native' /
                                         'yuv2rgb_cv2_tables.h'))
    ap.add_argument('--skip-synthetic', action='store_true')
    ns = ap.parse_args()

    videos = list(ns.videos or [])
    if not videos:
        videos = sorted(glob.glob('/root/reference/sample/*.mp4'))
    with tempfile.TemporaryDirectory() as td:
        if not ns.skip_synthetic:
            videos += write_synthetic(td)
        print('collecting observations...', file=sys.stderr)
        R, B, G = collect(videos)
        TR = solve_tables(R, 2, 'R')
        TB = solve_tables(B, 2, 'B')
        TG = solve_tables(G, 3, 'G')
    emit_header({'kTY_R': TR[0], 'kTV_R': TR[1],
                 'kTY_G': TG[0], 'kTU_G': TG[1], 'kTV_G': TG[2],
                 'kTY_B': TB[0], 'kTU_B': TB[1]},
                ns.out, len(R) + len(B) + len(G))
    return 0


if __name__ == '__main__':
    raise SystemExit(main())
