#!/usr/bin/env python3
"""Diff two bench records (BENCH_*.json) rung by rung.

The driver stamps one ``BENCH_r{N}.json`` per round; this tool turns two
of them into an honest regression report instead of eyeballing JSON:

    python tools/bench_diff.py BENCH_r04.json BENCH_r05.json
    python tools/bench_diff.py old.json new.json --fail-on-regression 10

Direction-aware: throughput-like rungs (``*clips_per_sec*``,
``*videos_per_min*``, ``*hit_rate*``, ``*occupancy*``, ``value``,
``vs_baseline``, ``*_speedup``, and the fused worklist's
``*_amortization`` ratios — decode/hash passes amortized across
families, → N when fusion works) regress when they DROP;
latency/duration-like rungs (``*latency*``, ``*_s`` suffixed) regress
when they RISE. Numeric MEASURED-ERROR rungs (``*_error*`` fields the
precision-ladder lanes record — bf16 and int8 alike:
``*_max_abs_error`` / ``*_rel_l2_error``) are
lower-is-better for display but FLAGGED-NEVER-GATED like config
metadata — drift there is bounded by tests/test_precision.py's pinned
per-family bounds, not by a cross-round percentage (random-weight
magnitudes make percent-of-error noise). Non-numeric rungs (exception
strings) and rungs present on only one side are listed but never
counted as regressions — an absent rung usually means a different
BENCH_* env, not a slowdown. Config-metadata rungs (``*_inflight``,
``*_decode_workers``, ``*_mesh_devices``, ``*_families`` — they name
the loop configuration or family set a number ran under) are flagged
``config-changed`` when they differ, never counted as regressions.

``--fail-on-regression PCT`` exits 1 if any shared numeric rung
regressed by more than PCT percent (CI gate); exit 0 otherwise; exit 2
on usage/IO errors.
"""
from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List, Optional, Tuple

# 'boot_first_feature' names the zero-cold-start rungs
# (serve_boot_first_feature[_cold]_s): boot-to-first-feature is a
# latency even though the name doesn't say so; the '_s' suffix rule
# would catch it too, but direction must not hinge on a suffix
# convention alone for a rung CI gates on
LOWER_IS_BETTER_MARKERS = ('latency', 'resume_pass', 'boot_first_feature')

# rungs that NAME the loop configuration a number was measured under
# (async depth, decode-farm worker count, mesh width, fused family set)
# rather than measuring anything — a change there is a config change to
# flag, never a perf regression
CONFIG_METADATA_SUFFIXES = ('_inflight', '_decode_workers',
                            '_mesh_devices', '_compute_dtype',
                            '_families')


def is_config_metadata(name: str) -> bool:
    return name.endswith(CONFIG_METADATA_SUFFIXES)


def is_error_rung(name: str) -> bool:
    """Numeric measured-error rungs (the precision ladder's
    ``*_max_abs_error`` / ``*_rel_l2_error`` fields — every bf16 and
    int8 rung records them). Lower is better, but NEVER gated:
    their absolute bound lives in tests/test_precision.py — a
    percentage diff across rounds (different weights, geometry,
    platform) is noise, not signal. Suffix-matched exactly: a future
    numeric rung that merely CONTAINS 'error' (an error-rate counter,
    say) must still gate like any other measurement. The ``*_error``
    exception-string rungs are non-numeric and already fall out as
    n/a."""
    return name.endswith(('_max_abs_error', '_rel_l2_error'))


def load_record(path: str) -> Dict[str, Any]:
    """A bench record in any of its shipped shapes: the one-JSON-line
    file the driver contract produces, a raw (possibly pretty-printed)
    record dict, or the driver's round wrapper (``BENCH_r{N}.json``:
    ``{n, cmd, rc, tail, parsed}`` with the record under ``parsed``)."""
    with open(path) as f:
        text = f.read().strip()
    if not text:
        rec: Any = {}
    else:
        try:
            rec = json.loads(text)            # whole file (pretty or flat)
        except json.JSONDecodeError:
            rec = json.loads(text.splitlines()[0])   # one-line contract
    if isinstance(rec, dict) and 'rungs' not in rec \
            and isinstance(rec.get('parsed'), dict):
        rec = rec['parsed']                   # driver round wrapper
    if not isinstance(rec, dict):
        raise ValueError(f'{path}: not a JSON object')
    return rec


def flatten_rungs(rec: Dict[str, Any]) -> Dict[str, Any]:
    """Headline value + every rung, one flat comparable namespace."""
    out: Dict[str, Any] = {}
    if isinstance(rec.get('value'), (int, float)):
        out['value'] = rec['value']
    if isinstance(rec.get('vs_baseline'), (int, float)):
        out['vs_baseline'] = rec['vs_baseline']
    for k, v in (rec.get('rungs') or {}).items():
        out[k] = v
    return out


def lower_is_better(name: str) -> bool:
    if any(m in name for m in LOWER_IS_BETTER_MARKERS):
        return True
    if is_error_rung(name):
        return True
    return name.endswith('_s') and 'per_sec' not in name


def compare(old: Dict[str, Any], new: Dict[str, Any]
            ) -> List[Tuple[str, Any, Any, Optional[float]]]:
    """(name, old, new, regression_pct|None) per rung; regression_pct is
    positive when the rung got WORSE (direction-aware), None when the
    rung is not comparable (non-numeric, one-sided, old == 0)."""
    rows = []
    for name in sorted(set(old) | set(new)):
        a, b = old.get(name), new.get(name)
        reg: Optional[float] = None
        if isinstance(a, (int, float)) and isinstance(b, (int, float)) \
                and not isinstance(a, bool) and not isinstance(b, bool) \
                and a != 0 and not is_config_metadata(name):
            change = (b - a) / abs(a) * 100.0
            reg = change if lower_is_better(name) else -change
        rows.append((name, a, b, reg))
    return rows


def main(argv: List[str] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument('old', help='baseline bench JSON')
    ap.add_argument('new', help='candidate bench JSON')
    ap.add_argument('--fail-on-regression', type=float, metavar='PCT',
                    default=None,
                    help='exit 1 if any shared numeric rung regressed '
                         'by more than PCT percent')
    args = ap.parse_args(argv)

    try:
        old = flatten_rungs(load_record(args.old))
        new = flatten_rungs(load_record(args.new))
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f'bench_diff: {e}', file=sys.stderr)
        return 2

    rows = compare(old, new)
    width = max((len(r[0]) for r in rows), default=4)
    print(f'{"rung".ljust(width)} | {"old":>12} | {"new":>12} | change')
    regressions = []
    for name, a, b, reg in rows:
        if reg is None:
            note = ('only-old' if name not in new
                    else 'only-new' if name not in old
                    else 'config-changed' if is_config_metadata(name)
                    and a != b else
                    'config' if is_config_metadata(name) else 'n/a')
            print(f'{name.ljust(width)} | {str(a):>12} | {str(b):>12} '
                  f'| {note}')
            continue
        arrow = 'WORSE' if reg > 0 else 'better' if reg < 0 else 'same'
        # measured-error rungs are flagged, never gated (their absolute
        # bound is test-pinned; cross-round percentages are noise)
        flag = ' (error rung: never gated)' if is_error_rung(name) else ''
        # reg is worsening%; report the signed raw change for readability
        change = (b - a) / abs(a) * 100.0
        print(f'{name.ljust(width)} | {a:>12.4g} | {b:>12.4g} '
              f'| {change:+7.2f}% {arrow}{flag}')
        if args.fail_on_regression is not None \
                and reg > args.fail_on_regression \
                and not is_error_rung(name):
            regressions.append((name, reg))

    if regressions:
        for name, reg in regressions:
            print(f'bench_diff: REGRESSION {name}: {reg:.2f}% worse '
                  f'(threshold {args.fail_on_regression}%)',
                  file=sys.stderr)
        return 1
    return 0


if __name__ == '__main__':
    raise SystemExit(main())
