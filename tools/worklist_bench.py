#!/usr/bin/env python3
"""Sustained multi-video worklist benchmark (VERDICT r4 task 5).

The north-star workload is a corpus (BASELINE.md: 20K Kinetics clips),
not one stack batch: this tool runs N videos through the REAL extraction
loop — the same fault-isolated per-video `_extract` the CLI runs
(cli.py:69-71), with the resume contract, prefetch pipelining, and
decode/compute overlap all live — and reports videos/min, aggregate
clips/s, and the per-stage wall-time split from the production Tracer.

The worklist is N byte-copies of a source clip under distinct stems
(identical decode cost per item, distinct resume keys — what a sharded
corpus looks like to one worker). A second pass over the same worklist
measures the resume path (everything skips) — the already-done check
must stay O(corpus) cheap or restarts of pod-scale jobs burn hours.

Usage:
    python tools/worklist_bench.py                    # real TPU, i3d, N=4
    BENCH_PLATFORM=cpu N_VIDEOS=2 WORKLIST_SECONDS=2 \
        python tools/worklist_bench.py                # smoke

Prints one JSON record per mode on stdout — the per-video loop first,
then the packed corpus pipeline (``pack_across_videos=true``: batch-major
across videos, parallel/packing.py) three times, pinning one knob per
step so every delta is attributable: ``inflight=1 decode_workers=1``
(the synchronous single-process baseline), ``inflight=2`` (the
deferred-D2H async device loop), and ``inflight=2 decode_workers=N``
(the multi-process decode farm, farm/ — N = ``BENCH_DECODE_WORKERS``,
default 4 on accelerators / 2 on CPU), then ``mesh_devices=N`` (the
mesh-sharded device loop: batches plan at capacity × N and shard over
N chips — ``BENCH_MESH_DEVICES``, default every local device), each
with its batch-occupancy
figure; bench.py embeds them as the ``worklist_clips_per_sec``,
``worklist_packed_clips_per_sec``, ``worklist_async_clips_per_sec``,
``worklist_farm_clips_per_sec``, and ``worklist_mesh_clips_per_sec``
rungs. Every record carries the ``inflight`` depth, ``decode_workers``
count, and resolved ``mesh_devices`` width it ran at.

``BENCH_FUSED=1`` adds the fused multi-family record
(``run_worklist_fused``): one ``features=[...]`` pass decoding and
sha256-hashing each video ONCE vs N sequential per-family passes, with
the wall-clock speedup and the decode / hash amortization ratios —
bench.py embeds it as the ``worklist_fused_*`` rungs.
"""
from __future__ import annotations

import json
import os
import shutil
import sys
import time
from pathlib import Path

import numpy as np

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))


def bench_decode_workers(on_accel: bool) -> int:
    """The ONE place the ``worklist_farm_*`` rung's worker count comes
    from (``BENCH_DECODE_WORKERS`` override, else 4 on accelerators /
    2 on CPU) — bench.py imports this so both tools' farm rungs always
    run the same configuration under the same rung name."""
    return int(os.environ.get('BENCH_DECODE_WORKERS',
                              4 if on_accel else 2))


def bench_mesh_devices() -> int:
    """The ONE place the ``worklist_mesh_*`` rung's device count comes
    from: ``BENCH_MESH_DEVICES`` override, else every local device (the
    near-linear-scaling headline wants the whole slice; CPU CI forces 2
    virtual host devices via ``--xla_force_host_platform_device_count``).
    Returns at least 1 — on a single-device host the rung still runs,
    its metadata naming the degenerate width."""
    n = int(os.environ.get('BENCH_MESH_DEVICES', 0))
    if n == 0:
        import jax
        n = len(jax.local_devices())
    return max(n, 1)


# the fused rung's per-family models: offline-safe picks (random-weight
# capable, no hub download) whose decode signatures all fuse — resnet /
# clip / timm share ('framewise', None, None, 'auto')
_FUSED_MODELS = {'resnet': 'resnet18', 'clip': 'ViT-B/32',
                 'timm': 'vit_tiny_patch16_224'}


def bench_fused_features() -> list:
    """The ONE place the ``worklist_fused_*`` rung's family set comes
    from (``BENCH_FUSED_FEATURES`` override, comma-separated, default
    ``resnet,clip,timm``) — bench.py imports this so both tools' fused
    rungs always run the same family set under the same rung name."""
    raw = os.environ.get('BENCH_FUSED_FEATURES', 'resnet,clip,timm')
    return [f.strip() for f in raw.split(',') if f.strip()]


def make_worklist(tmp_dir: str, n_videos: int, seconds: float) -> list:
    """N distinct-stem byte-copies of the source clip.

    Source selection delegates to bench.py's ``_bench_video`` — the ONE
    place that picks the benchmark clip (reference sample when present,
    synthetic fallback otherwise; ``BENCH_VIDEO=synthetic`` forces the
    fallback) — so the worklist and e2e rungs always measure the same
    content. ``seconds`` applies to the synthetic fallback only; a
    too-short source surfaces loudly via run_worklist's clips>0 guard."""
    from bench import _bench_video
    src = _bench_video(tmp_dir, seconds=str(seconds))
    paths = []
    for i in range(n_videos):
        dst = Path(tmp_dir) / f'worklist_{i:04d}.mp4'
        shutil.copyfile(src, dst)
        paths.append(str(dst))
    return paths


def run_worklist(feature_type: str, paths: list, out_dir: str,
                 tmp_dir: str, platform: str, batch_size: int = 8,
                 stack: int = 16, precision: str = None,
                 packed: bool = False, inflight: int = None,
                 decode_workers: int = None, mesh_devices: int = None,
                 compute_dtype: str = None):
    """One timed pass of the real worklist loop; returns the record.

    ``packed=False`` times the per-video loop cli.py runs by default;
    ``packed=True`` times the batch-major corpus pipeline
    (``pack_across_videos=true`` → ``extract_packed``, parallel/packing.py)
    and additionally reports the compiled step's batch occupancy.
    ``inflight`` pins the output-side pipelining depth (1 = synchronous
    D2H after every dispatch; default = the config's async depth) — the
    resolved value rides in the record so every rung names the loop it
    measured. ``decode_workers`` pins the input side (1 = in-process
    decode; >1 on the packed path = the multi-process decode farm,
    farm/) and likewise rides in the record. ``mesh_devices`` pins the
    packed loop's data-parallel mesh width (1 = single chip; N shards
    capacity × N batches over N chips, parallel/mesh.py) — the RESOLVED
    width rides in the record. The extractor is created
    once (matching cli.py) so compile caches, weights, and the decode
    service amortize across the worklist the way they do in
    production."""
    from video_features_tpu.config import load_config
    from video_features_tpu.registry import create_extractor
    from video_features_tpu.utils.tracing import round_report

    if precision is None:
        precision = os.environ.get('BENCH_PRECISION', 'mixed')
    overrides = {
        'video_paths': paths,
        'device': platform,
        'precision': precision,
        'batch_size': batch_size,
        'allow_random_weights': True,
        'profile': True,                       # per-stage Tracer on
        'pack_across_videos': packed,
        'on_extraction': 'save_numpy',         # resume contract is real
        'output_path': os.path.join(out_dir, 'out'),
        'tmp_path': os.path.join(tmp_dir, 'tmp'),
    }
    if feature_type in ('i3d', 'r21d', 's3d'):
        overrides.update({'stack_size': stack, 'step_size': stack})
    if inflight is not None:
        overrides['inflight'] = int(inflight)
    if decode_workers is not None:
        overrides['decode_workers'] = int(decode_workers)
    if mesh_devices is not None:
        overrides['mesh_devices'] = int(mesh_devices)
    if compute_dtype is not None:
        # the bf16 fast lane (ops/precision.py): outputs are NOT
        # byte-identical to float32's — the *_bf16_* rungs record the
        # measured error next to the speedup for exactly that reason
        overrides['compute_dtype'] = str(compute_dtype)
    args = load_config(feature_type, overrides=overrides)
    ex = create_extractor(args)

    def run_pass(worklist):
        if packed:
            ex.extract_packed(worklist)
        else:
            for p in worklist:
                ex._extract(p)

    # warm pass on the FIRST video only: compile time is a per-process
    # constant, not a per-video term — excluding it measures the
    # sustained rate a long worklist converges to
    run_pass(paths[:1])
    warm_outputs = [f for f in Path(ex.output_path).rglob('*') if f.is_file()]
    assert warm_outputs, (
        'warm pass produced no outputs — extraction failed before the '
        'timed loop (see stderr); aborting rather than timing compiles')
    for sub in warm_outputs:
        sub.unlink()
    ex.tracer.reset()
    # _extract resets the tracer after every video (per-video tables);
    # suppress that during the timed loop so stages accumulate worklist-
    # wide, then restore
    real_reset = ex.tracer.reset
    ex.tracer.reset = lambda: None

    t0 = time.perf_counter()
    run_pass(paths)                           # the cli.py loop, timed
    elapsed = time.perf_counter() - t0
    stages = ex.tracer.report()
    ex.tracer.reset = real_reset
    ex.tracer.reset()

    # clips actually produced (from the saved outputs — the real contract)
    from video_features_tpu.utils.output import make_path
    keys = ex._saved_feat_keys()
    clips = 0
    for p in paths:
        fpath = make_path(ex.output_path, p, keys[0], '.npy')
        if Path(fpath).exists():
            arr = np.load(fpath, allow_pickle=True)
            if getattr(arr, 'ndim', 0) >= 1:
                clips += arr.shape[0]

    # success guard: _extract fault-isolates per video, so a worklist of
    # failures would otherwise "complete" fast and record a bogus rate
    assert clips > 0, (
        f'worklist produced 0 clips over {len(paths)} videos — extraction '
        'failed (see stderr) or the source clip is shorter than one stack')

    t1 = time.perf_counter()
    run_pass(paths)                           # resume pass: all skip
    resume_elapsed = time.perf_counter() - t1

    occupancy = stages.get('model', {}).get('occupancy')
    return {
        'feature_type': feature_type,
        'precision': precision,
        'packed': packed,
        # the output-side pipelining depth this rung actually ran at
        # (1 = synchronous loop) — rung metadata, so a BENCH_*.json
        # says which device loop produced its number
        'inflight': int(args.get('inflight', 1)),
        # the input side's decode parallelism (1 = in-process; >1 packed
        # = the decode farm) — rung metadata like inflight
        'decode_workers': int(args.get('decode_workers', 1)),
        # the RESOLVED mesh width the packed loop sharded over (1 =
        # single chip; mesh_devices=0 auto-detect resolves here) —
        # config metadata naming the device set behind the number
        'mesh_devices': int(getattr(ex, '_packed_mesh_ndev', 1) or 1),
        # the precision lane the step computed in ('float32' default;
        # 'bfloat16' = the fast lane) — rung metadata like inflight
        'compute_dtype': str(getattr(ex, 'compute_dtype', 'float32')),
        'n_videos': len(paths),
        'videos_per_min': round(len(paths) / elapsed * 60, 3),
        'clips_total': int(clips),
        'clips_per_sec': round(clips / elapsed, 3),
        'batch_occupancy': (round(occupancy, 4)
                            if occupancy is not None else None),
        'resume_pass_s': round(resume_elapsed, 4),
        # the FULL per-stage Tracer report (not just totals): bench.py
        # embeds it under the record's stage_reports so a BENCH_*.json
        # carries the wall-time split behind every rung
        'stages': round_report(stages),
    }


def run_worklist_fused(families: list, paths: list, out_dir: str,
                       tmp_dir: str, platform: str, batch_size: int = 8,
                       precision: str = None):
    """One fused multi-family pass vs N sequential passes; returns the
    record behind the ``worklist_fused_*`` rungs.

    The fused pass drives every family through ONE decode stream per
    video (``run_packed_fused``, parallel/packing.py) while the
    sequential baseline runs each family's own ``extract_packed`` over
    the same worklist — the exact N-runs-of-the-CLI comparison the
    ``features=[...]`` config replaces. Three ratios ride in the record:

      * ``fused_speedup`` — sequential wall over fused wall (the
        headline: what a corpus owner saves by fusing);
      * ``decode_amortization`` — sequential decode+preprocess seconds
        over fused (→ N for N fully-amortized families);
      * ``hash_amortization`` — sequential sha256 passes over fused
        (the content-cache keying cost; fused hashes each video once).

    Every pass runs over FRESH byte-copies of the worklist: distinct
    paths keep the stat-keyed ``hash_file`` memo provably cold per pass
    (each sequential family pass models its own CLI process) and keep
    resume sidecars from turning later passes into all-skip no-ops.
    A byte-parity sweep over the outputs guards the speedup claim —
    a fused run that diverged from sequential must not record a rate.
    """
    from video_features_tpu.cache.key import (
        hash_file_stats, reset_hash_file_stats,
    )
    from video_features_tpu.cache.store import FeatureCache
    from video_features_tpu.config import load_fused_configs
    from video_features_tpu.parallel.packing import (
        FusedTask, VideoTask, run_packed_fused,
    )
    from video_features_tpu.registry import create_extractor
    from video_features_tpu.utils.output import make_path
    from video_features_tpu.utils.tracing import round_report

    if precision is None:
        precision = os.environ.get('BENCH_PRECISION', 'mixed')
    overrides = {
        'video_paths': paths,
        'device': platform,
        'precision': precision,
        'batch_size': batch_size,
        'allow_random_weights': True,
        'profile': True,                       # per-stage Tracer on
        'pack_across_videos': True,
        'on_extraction': 'save_numpy',
        'output_path': os.path.join(out_dir, 'out'),
        'tmp_path': os.path.join(tmp_dir, 'fused_tmp'),
    }
    for fam in families:
        if fam in _FUSED_MODELS:
            overrides[f'{fam}.model_name'] = _FUSED_MODELS[fam]
    configs = load_fused_configs(families, overrides=overrides)
    exs = {fam: create_extractor(cfg) for fam, cfg in configs.items()}
    sigs = {fam: ex.fused_decode_signature() for fam, ex in exs.items()}
    assert len(set(sigs.values())) == 1 and None not in sigs.values(), (
        f'fused rung families must share one decode signature: {sigs}')

    def copies(tag):
        d = Path(tmp_dir) / f'copies_{tag}'
        d.mkdir(parents=True, exist_ok=True)
        return [str(shutil.copyfile(p, str(d / Path(p).name)) or
                    d / Path(p).name) for p in paths]

    def fused_tasks(worklist, tag):
        tasks = []
        for p in worklist:
            c = FusedTask(p, list(exs))
            for fam, sub in c.subtasks.items():
                sub.out_root = os.path.join(out_dir, tag, fam)
            tasks.append(c)
        return tasks

    def decode_total(rep):
        return sum(rep.get(k, {}).get('total_s', 0.0)
                   for k in ('decode', 'decode+preprocess'))

    # warm pass (fused) compiles every family's programs — the fused
    # packer pools per family at each family's own batch size, so these
    # are the SAME program identities the sequential passes reuse
    run_packed_fused(exs, fused_tasks(copies('warm'), 'warm'))
    warm = [f for f in Path(out_dir, 'warm').rglob('*.npy')]
    assert warm, (
        'fused warm pass produced no outputs — extraction failed before '
        'the timed loop (see stderr); aborting rather than timing compiles')

    # suppress per-video tracer resets so stages accumulate per phase;
    # the saved bound methods reset between phases and restore at the end
    real_resets = {fam: ex.tracer.reset for fam, ex in exs.items()}
    for ex in exs.values():
        ex.tracer.reset = lambda: None
    try:
        for reset in real_resets.values():
            reset()

        # -- sequential baseline: one extract_packed pass per family,
        # each over its own worklist copies + its own content cache
        # (modeling N separate CLI processes: cold sha256 memo each)
        seq_wall = seq_decode = 0.0
        seq_hash_passes = 0
        for fam, ex in exs.items():
            assert ex.run_fingerprint is not None, fam
            wl = copies(f'seq_{fam}')
            tasks = [VideoTask(p, out_root=os.path.join(out_dir, 'seq', fam))
                     for p in wl]
            ex.cache = FeatureCache(os.path.join(tmp_dir, 'cache_seq', fam))
            reset_hash_file_stats()
            t0 = time.perf_counter()
            ex.extract_packed(tasks)
            seq_wall += time.perf_counter() - t0
            seq_hash_passes += hash_file_stats()['passes']
            seq_decode += decode_total(ex.tracer.report())
            ex.cache = None
            real_resets[fam]()

        # -- the fused pass: one decode + one sha256 pass per video
        wl = copies('fused')
        tasks = fused_tasks(wl, 'fused')
        for fam, ex in exs.items():
            ex.cache = FeatureCache(os.path.join(tmp_dir, 'cache_fused',
                                                 fam))
        reset_hash_file_stats()
        t0 = time.perf_counter()
        run_packed_fused(exs, tasks)
        fused_wall = time.perf_counter() - t0
        fused_hash = hash_file_stats()
        lead = exs[next(iter(exs))]
        fused_stages = lead.tracer.report()
        fused_decode = decode_total(fused_stages)
        for ex in exs.values():
            ex.cache = None
    finally:
        for fam, ex in exs.items():
            ex.tracer.reset = real_resets[fam]
            ex.tracer.reset()

    # byte-parity sweep + clip count from the saved outputs (the real
    # contract): a fused run that diverged must not record a speedup
    clips = 0
    for fam, ex in exs.items():
        keys = ex._saved_feat_keys()
        for p in wl:
            fused_f = make_path(os.path.join(out_dir, 'fused', fam),
                                p, keys[0], '.npy')
            seq_f = make_path(os.path.join(out_dir, 'seq', fam),
                              p, keys[0], '.npy')
            a = np.load(fused_f, allow_pickle=True)
            b = np.load(seq_f, allow_pickle=True)
            assert np.array_equal(a, b), (
                f'fused outputs diverged from sequential: {fam} {p}')
            if getattr(a, 'ndim', 0) >= 1:
                clips += a.shape[0]
    assert clips > 0, (
        f'fused worklist produced 0 clips over {len(paths)} videos — '
        'extraction failed (see stderr) or the source clip is too short')

    return {
        'families': list(exs),
        'precision': precision,
        'n_videos': len(paths),
        'n_families': len(exs),
        'clips_total': int(clips),
        'clips_per_sec': round(clips / fused_wall, 3),
        'fused_wall_s': round(fused_wall, 4),
        'sequential_wall_s': round(seq_wall, 4),
        # the headline ratio: N sequential family passes over one fused
        # pass — higher is better, → N as decode dominates
        'fused_speedup': round(seq_wall / fused_wall, 4),
        'decode_s_sequential': round(seq_decode, 4),
        'decode_s_fused': round(fused_decode, 4),
        'decode_amortization': (round(seq_decode / fused_decode, 4)
                                if fused_decode > 0 else None),
        # sha256 content-keying passes: fused streams each video once
        'hash_passes_sequential': int(seq_hash_passes),
        'hash_passes_fused': int(fused_hash['passes']),
        'hash_amortization': (round(seq_hash_passes
                                    / fused_hash['passes'], 4)
                              if fused_hash['passes'] else None),
        # the lead tracer's fused-pass split (shared decode + the lead
        # family's device stages) — embedded under stage_reports
        'stages': round_report(fused_stages),
    }


def main() -> int:
    import contextlib
    import tempfile

    import jax
    if os.environ.get('BENCH_PLATFORM'):
        jax.config.update('jax_platforms', os.environ['BENCH_PLATFORM'])
    from video_features_tpu.utils.device import enable_compilation_cache

    platform = jax.devices()[0].platform
    on_accel = platform != 'cpu'
    enable_compilation_cache('~/.cache/video_features_tpu/xla', platform)
    n = int(os.environ.get('N_VIDEOS', 4 if on_accel else 2))
    seconds = float(os.environ.get('WORKLIST_SECONDS',
                                   10 if on_accel else 2))
    feature_type = os.environ.get('WORKLIST_FEATURE', 'i3d')
    stdout = sys.stdout
    # the loop's per-video prints (skip messages, warnings) belong on
    # stderr; stdout carries the JSON records only
    with tempfile.TemporaryDirectory() as td, \
            contextlib.redirect_stdout(sys.stderr):
        paths = make_worklist(td, n, seconds)
        batch = 8 if on_accel else 2
        stack = int(os.environ.get('BENCH_STACK', 16))
        rec = run_worklist(feature_type, paths, td, td, platform,
                           batch_size=batch, stack=stack)
        # packed mode writes under its own output root so the per-video
        # pass's resume files can't turn it into an all-skip no-op; only
        # families with packed support run it — an unsupported feature
        # must still emit its per-video record, not crash the tool
        from video_features_tpu.registry import PACKED_FEATURES
        rec_packed = rec_async = rec_farm = rec_mesh = None
        if feature_type in PACKED_FEATURES:
            # the packed ladder pins ONE knob per record so each delta
            # is attributable: sync in-process → async in-process →
            # async + decode farm.
            # inflight=1 decode_workers=1 pins the fully SYNCHRONOUS
            # single-process packed loop (the pre-async baseline)...
            rec_packed = run_worklist(feature_type, paths,
                                      os.path.join(td, 'packed'), td,
                                      platform, batch_size=batch,
                                      stack=stack, packed=True, inflight=1,
                                      decode_workers=1)
            # ...the async record adds only the deferred-D2H loop...
            rec_async = run_worklist(feature_type, paths,
                                     os.path.join(td, 'packed_async'), td,
                                     platform, batch_size=batch,
                                     stack=stack, packed=True, inflight=2,
                                     decode_workers=1)
            # ...and the farm record adds the multi-process decode farm
            # (farm/) on top — the full pipeline
            n_decode = bench_decode_workers(on_accel)
            rec_farm = run_worklist(feature_type, paths,
                                    os.path.join(td, 'packed_farm'), td,
                                    platform, batch_size=batch,
                                    stack=stack, packed=True, inflight=2,
                                    decode_workers=n_decode)
            # ...and the mesh record shards the async loop's batches
            # over N chips (capacity × N planning, parallel/mesh.py) —
            # the pod-scale rung; outputs stay byte-identical
            # (tests/test_mesh_packed.py)
            rec_mesh = run_worklist(feature_type, paths,
                                    os.path.join(td, 'packed_mesh'), td,
                                    platform, batch_size=batch,
                                    stack=stack, packed=True, inflight=2,
                                    decode_workers=1,
                                    mesh_devices=bench_mesh_devices())
        # the fused multi-family record is opt-in for the standalone
        # tool (it transplants one model per family); bench.py gates it
        # the same way under the worklist_fused_* rungs
        rec_fused = None
        if os.environ.get('BENCH_FUSED', '0') == '1':
            rec_fused = run_worklist_fused(bench_fused_features(), paths,
                                           os.path.join(td, 'fused'), td,
                                           platform, batch_size=batch)
    print(json.dumps(rec), file=stdout)
    for extra in (rec_packed, rec_async, rec_farm, rec_mesh, rec_fused):
        if extra is not None:
            print(json.dumps(extra), file=stdout)
    return 0


if __name__ == '__main__':
    raise SystemExit(main())
