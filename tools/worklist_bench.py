#!/usr/bin/env python3
"""Sustained multi-video worklist benchmark (VERDICT r4 task 5).

The north-star workload is a corpus (BASELINE.md: 20K Kinetics clips),
not one stack batch: this tool runs N videos through the REAL extraction
loop — the same fault-isolated per-video `_extract` the CLI runs
(cli.py:69-71), with the resume contract, prefetch pipelining, and
decode/compute overlap all live — and reports videos/min, aggregate
clips/s, and the per-stage wall-time split from the production Tracer.

The worklist is N byte-copies of a source clip under distinct stems
(identical decode cost per item, distinct resume keys — what a sharded
corpus looks like to one worker). A second pass over the same worklist
measures the resume path (everything skips) — the already-done check
must stay O(corpus) cheap or restarts of pod-scale jobs burn hours.

Usage:
    python tools/worklist_bench.py                    # real TPU, i3d, N=4
    BENCH_PLATFORM=cpu N_VIDEOS=2 WORKLIST_SECONDS=2 \
        python tools/worklist_bench.py                # smoke

Prints one JSON record per mode on stdout — the per-video loop first,
then the packed corpus pipeline (``pack_across_videos=true``: batch-major
across videos, parallel/packing.py) three times, pinning one knob per
step so every delta is attributable: ``inflight=1 decode_workers=1``
(the synchronous single-process baseline), ``inflight=2`` (the
deferred-D2H async device loop), and ``inflight=2 decode_workers=N``
(the multi-process decode farm, farm/ — N = ``BENCH_DECODE_WORKERS``,
default 4 on accelerators / 2 on CPU), then ``mesh_devices=N`` (the
mesh-sharded device loop: batches plan at capacity × N and shard over
N chips — ``BENCH_MESH_DEVICES``, default every local device), each
with its batch-occupancy
figure; bench.py embeds them as the ``worklist_clips_per_sec``,
``worklist_packed_clips_per_sec``, ``worklist_async_clips_per_sec``,
``worklist_farm_clips_per_sec``, and ``worklist_mesh_clips_per_sec``
rungs. Every record carries the ``inflight`` depth, ``decode_workers``
count, and resolved ``mesh_devices`` width it ran at.
"""
from __future__ import annotations

import json
import os
import shutil
import sys
import time
from pathlib import Path

import numpy as np

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))


def bench_decode_workers(on_accel: bool) -> int:
    """The ONE place the ``worklist_farm_*`` rung's worker count comes
    from (``BENCH_DECODE_WORKERS`` override, else 4 on accelerators /
    2 on CPU) — bench.py imports this so both tools' farm rungs always
    run the same configuration under the same rung name."""
    return int(os.environ.get('BENCH_DECODE_WORKERS',
                              4 if on_accel else 2))


def bench_mesh_devices() -> int:
    """The ONE place the ``worklist_mesh_*`` rung's device count comes
    from: ``BENCH_MESH_DEVICES`` override, else every local device (the
    near-linear-scaling headline wants the whole slice; CPU CI forces 2
    virtual host devices via ``--xla_force_host_platform_device_count``).
    Returns at least 1 — on a single-device host the rung still runs,
    its metadata naming the degenerate width."""
    n = int(os.environ.get('BENCH_MESH_DEVICES', 0))
    if n == 0:
        import jax
        n = len(jax.local_devices())
    return max(n, 1)


def make_worklist(tmp_dir: str, n_videos: int, seconds: float) -> list:
    """N distinct-stem byte-copies of the source clip.

    Source selection delegates to bench.py's ``_bench_video`` — the ONE
    place that picks the benchmark clip (reference sample when present,
    synthetic fallback otherwise; ``BENCH_VIDEO=synthetic`` forces the
    fallback) — so the worklist and e2e rungs always measure the same
    content. ``seconds`` applies to the synthetic fallback only; a
    too-short source surfaces loudly via run_worklist's clips>0 guard."""
    from bench import _bench_video
    src = _bench_video(tmp_dir, seconds=str(seconds))
    paths = []
    for i in range(n_videos):
        dst = Path(tmp_dir) / f'worklist_{i:04d}.mp4'
        shutil.copyfile(src, dst)
        paths.append(str(dst))
    return paths


def run_worklist(feature_type: str, paths: list, out_dir: str,
                 tmp_dir: str, platform: str, batch_size: int = 8,
                 stack: int = 16, precision: str = None,
                 packed: bool = False, inflight: int = None,
                 decode_workers: int = None, mesh_devices: int = None,
                 compute_dtype: str = None):
    """One timed pass of the real worklist loop; returns the record.

    ``packed=False`` times the per-video loop cli.py runs by default;
    ``packed=True`` times the batch-major corpus pipeline
    (``pack_across_videos=true`` → ``extract_packed``, parallel/packing.py)
    and additionally reports the compiled step's batch occupancy.
    ``inflight`` pins the output-side pipelining depth (1 = synchronous
    D2H after every dispatch; default = the config's async depth) — the
    resolved value rides in the record so every rung names the loop it
    measured. ``decode_workers`` pins the input side (1 = in-process
    decode; >1 on the packed path = the multi-process decode farm,
    farm/) and likewise rides in the record. ``mesh_devices`` pins the
    packed loop's data-parallel mesh width (1 = single chip; N shards
    capacity × N batches over N chips, parallel/mesh.py) — the RESOLVED
    width rides in the record. The extractor is created
    once (matching cli.py) so compile caches, weights, and the decode
    service amortize across the worklist the way they do in
    production."""
    from video_features_tpu.config import load_config
    from video_features_tpu.registry import create_extractor
    from video_features_tpu.utils.tracing import round_report

    if precision is None:
        precision = os.environ.get('BENCH_PRECISION', 'mixed')
    overrides = {
        'video_paths': paths,
        'device': platform,
        'precision': precision,
        'batch_size': batch_size,
        'allow_random_weights': True,
        'profile': True,                       # per-stage Tracer on
        'pack_across_videos': packed,
        'on_extraction': 'save_numpy',         # resume contract is real
        'output_path': os.path.join(out_dir, 'out'),
        'tmp_path': os.path.join(tmp_dir, 'tmp'),
    }
    if feature_type in ('i3d', 'r21d', 's3d'):
        overrides.update({'stack_size': stack, 'step_size': stack})
    if inflight is not None:
        overrides['inflight'] = int(inflight)
    if decode_workers is not None:
        overrides['decode_workers'] = int(decode_workers)
    if mesh_devices is not None:
        overrides['mesh_devices'] = int(mesh_devices)
    if compute_dtype is not None:
        # the bf16 fast lane (ops/precision.py): outputs are NOT
        # byte-identical to float32's — the *_bf16_* rungs record the
        # measured error next to the speedup for exactly that reason
        overrides['compute_dtype'] = str(compute_dtype)
    args = load_config(feature_type, overrides=overrides)
    ex = create_extractor(args)

    def run_pass(worklist):
        if packed:
            ex.extract_packed(worklist)
        else:
            for p in worklist:
                ex._extract(p)

    # warm pass on the FIRST video only: compile time is a per-process
    # constant, not a per-video term — excluding it measures the
    # sustained rate a long worklist converges to
    run_pass(paths[:1])
    warm_outputs = [f for f in Path(ex.output_path).rglob('*') if f.is_file()]
    assert warm_outputs, (
        'warm pass produced no outputs — extraction failed before the '
        'timed loop (see stderr); aborting rather than timing compiles')
    for sub in warm_outputs:
        sub.unlink()
    ex.tracer.reset()
    # _extract resets the tracer after every video (per-video tables);
    # suppress that during the timed loop so stages accumulate worklist-
    # wide, then restore
    real_reset = ex.tracer.reset
    ex.tracer.reset = lambda: None

    t0 = time.perf_counter()
    run_pass(paths)                           # the cli.py loop, timed
    elapsed = time.perf_counter() - t0
    stages = ex.tracer.report()
    ex.tracer.reset = real_reset
    ex.tracer.reset()

    # clips actually produced (from the saved outputs — the real contract)
    from video_features_tpu.utils.output import make_path
    keys = ex._saved_feat_keys()
    clips = 0
    for p in paths:
        fpath = make_path(ex.output_path, p, keys[0], '.npy')
        if Path(fpath).exists():
            arr = np.load(fpath, allow_pickle=True)
            if getattr(arr, 'ndim', 0) >= 1:
                clips += arr.shape[0]

    # success guard: _extract fault-isolates per video, so a worklist of
    # failures would otherwise "complete" fast and record a bogus rate
    assert clips > 0, (
        f'worklist produced 0 clips over {len(paths)} videos — extraction '
        'failed (see stderr) or the source clip is shorter than one stack')

    t1 = time.perf_counter()
    run_pass(paths)                           # resume pass: all skip
    resume_elapsed = time.perf_counter() - t1

    occupancy = stages.get('model', {}).get('occupancy')
    return {
        'feature_type': feature_type,
        'precision': precision,
        'packed': packed,
        # the output-side pipelining depth this rung actually ran at
        # (1 = synchronous loop) — rung metadata, so a BENCH_*.json
        # says which device loop produced its number
        'inflight': int(args.get('inflight', 1)),
        # the input side's decode parallelism (1 = in-process; >1 packed
        # = the decode farm) — rung metadata like inflight
        'decode_workers': int(args.get('decode_workers', 1)),
        # the RESOLVED mesh width the packed loop sharded over (1 =
        # single chip; mesh_devices=0 auto-detect resolves here) —
        # config metadata naming the device set behind the number
        'mesh_devices': int(getattr(ex, '_packed_mesh_ndev', 1) or 1),
        # the precision lane the step computed in ('float32' default;
        # 'bfloat16' = the fast lane) — rung metadata like inflight
        'compute_dtype': str(getattr(ex, 'compute_dtype', 'float32')),
        'n_videos': len(paths),
        'videos_per_min': round(len(paths) / elapsed * 60, 3),
        'clips_total': int(clips),
        'clips_per_sec': round(clips / elapsed, 3),
        'batch_occupancy': (round(occupancy, 4)
                            if occupancy is not None else None),
        'resume_pass_s': round(resume_elapsed, 4),
        # the FULL per-stage Tracer report (not just totals): bench.py
        # embeds it under the record's stage_reports so a BENCH_*.json
        # carries the wall-time split behind every rung
        'stages': round_report(stages),
    }


def main() -> int:
    import contextlib
    import tempfile

    import jax
    if os.environ.get('BENCH_PLATFORM'):
        jax.config.update('jax_platforms', os.environ['BENCH_PLATFORM'])
    from video_features_tpu.utils.device import enable_compilation_cache

    platform = jax.devices()[0].platform
    on_accel = platform != 'cpu'
    enable_compilation_cache('~/.cache/video_features_tpu/xla', platform)
    n = int(os.environ.get('N_VIDEOS', 4 if on_accel else 2))
    seconds = float(os.environ.get('WORKLIST_SECONDS',
                                   10 if on_accel else 2))
    feature_type = os.environ.get('WORKLIST_FEATURE', 'i3d')
    stdout = sys.stdout
    # the loop's per-video prints (skip messages, warnings) belong on
    # stderr; stdout carries the JSON records only
    with tempfile.TemporaryDirectory() as td, \
            contextlib.redirect_stdout(sys.stderr):
        paths = make_worklist(td, n, seconds)
        batch = 8 if on_accel else 2
        stack = int(os.environ.get('BENCH_STACK', 16))
        rec = run_worklist(feature_type, paths, td, td, platform,
                           batch_size=batch, stack=stack)
        # packed mode writes under its own output root so the per-video
        # pass's resume files can't turn it into an all-skip no-op; only
        # families with packed support run it — an unsupported feature
        # must still emit its per-video record, not crash the tool
        from video_features_tpu.registry import PACKED_FEATURES
        rec_packed = rec_async = rec_farm = rec_mesh = None
        if feature_type in PACKED_FEATURES:
            # the packed ladder pins ONE knob per record so each delta
            # is attributable: sync in-process → async in-process →
            # async + decode farm.
            # inflight=1 decode_workers=1 pins the fully SYNCHRONOUS
            # single-process packed loop (the pre-async baseline)...
            rec_packed = run_worklist(feature_type, paths,
                                      os.path.join(td, 'packed'), td,
                                      platform, batch_size=batch,
                                      stack=stack, packed=True, inflight=1,
                                      decode_workers=1)
            # ...the async record adds only the deferred-D2H loop...
            rec_async = run_worklist(feature_type, paths,
                                     os.path.join(td, 'packed_async'), td,
                                     platform, batch_size=batch,
                                     stack=stack, packed=True, inflight=2,
                                     decode_workers=1)
            # ...and the farm record adds the multi-process decode farm
            # (farm/) on top — the full pipeline
            n_decode = bench_decode_workers(on_accel)
            rec_farm = run_worklist(feature_type, paths,
                                    os.path.join(td, 'packed_farm'), td,
                                    platform, batch_size=batch,
                                    stack=stack, packed=True, inflight=2,
                                    decode_workers=n_decode)
            # ...and the mesh record shards the async loop's batches
            # over N chips (capacity × N planning, parallel/mesh.py) —
            # the pod-scale rung; outputs stay byte-identical
            # (tests/test_mesh_packed.py)
            rec_mesh = run_worklist(feature_type, paths,
                                    os.path.join(td, 'packed_mesh'), td,
                                    platform, batch_size=batch,
                                    stack=stack, packed=True, inflight=2,
                                    decode_workers=1,
                                    mesh_devices=bench_mesh_devices())
    print(json.dumps(rec), file=stdout)
    for extra in (rec_packed, rec_async, rec_farm, rec_mesh):
        if extra is not None:
            print(json.dumps(extra), file=stdout)
    return 0


if __name__ == '__main__':
    raise SystemExit(main())
