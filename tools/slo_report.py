#!/usr/bin/env python3
"""SLO burn-rate report: objectives, per-window burn, alert states.

Connects to a serve daemon OR a fleet router loopback port, reads the
``slo`` section of its metrics document (``obs/slo.py`` — a daemon
carries it when ``slo_latency_p99_s=`` / ``slo_availability=`` are
set; a router always does, over its routed-request families), and
renders one line per (objective, window) with the alert verdict.

A burn rate of 1.0 means the error budget is being spent exactly at
the sustainable pace; the alert fires when EVERY window burns above
the threshold (default 14.4x — a 30-day budget gone in ~2 days).

Usage:
    python tools/slo_report.py [--host 127.0.0.1] --port 9310 [--json]

Exit codes (monitorable — cron/CI can alert on them):
    0  SLO evaluation enabled, no alert firing
    1  at least one burn-rate alert is FIRING
    2  the target is unreachable, or answers a metrics document with
       SLO evaluation disabled (nothing to report)
"""
from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument('--host', default='127.0.0.1',
                    help='the daemon/router host (default: loopback)')
    ap.add_argument('--port', type=int, required=True,
                    help='a serve daemon or fleet router loopback port')
    ap.add_argument('--timeout-s', type=float, default=5.0,
                    help='connect deadline for reaching the target')
    ap.add_argument('--json', action='store_true',
                    help='print the raw slo section instead of the '
                         'report')
    ns = ap.parse_args(argv)

    from video_features_tpu.serve.client import ServeClient, ServeError
    try:
        doc = ServeClient(ns.port, host=ns.host,
                          connect_timeout_s=ns.timeout_s).metrics()
    except (ServeError, OSError) as e:
        print(f'error: {ns.host}:{ns.port} unreachable: {e}',
              file=sys.stderr)
        return 2
    # a router nests its document under 'fleet'; a daemon is flat
    slo = (doc.get('fleet') or doc).get('slo')
    if not isinstance(slo, dict) or not slo.get('enabled'):
        print(f'error: {ns.host}:{ns.port} has SLO evaluation disabled '
              '(set slo_latency_p99_s= / slo_availability= on the '
              'daemon; the fleet router always evaluates)',
              file=sys.stderr)
        return 2

    if ns.json:
        print(json.dumps(slo, sort_keys=True))
    else:
        objectives = slo.get('objectives') or {}
        alerts = slo.get('alerts') or {}
        threshold = slo.get('burn_alert_threshold')
        print(f"slo report {ns.host}:{ns.port}  "
              f"objectives={json.dumps(objectives, sort_keys=True)}  "
              f"alert_threshold={threshold}x")
        burn = slo.get('burn_rates') or {}
        for objective in sorted(burn):
            windows = burn[objective] or {}
            rendered = '  '.join(f'{w}={windows[w]:.2f}x'
                                 for w in sorted(windows))
            key = 'latency_p99' if objective == 'latency' else objective
            verdict = 'FIRING' if alerts.get(key) else 'ok'
            print(f'  {objective:<14} {rendered}  [{verdict}]')
        print(f"alerts firing: {slo.get('alerts_firing', 0)}  "
              f"(lifetime transitions: {slo.get('alerts_total', 0)})")

    return 1 if slo.get('alerts_firing') else 0


if __name__ == '__main__':
    raise SystemExit(main())
