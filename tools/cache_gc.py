#!/usr/bin/env python3
"""Offline maintenance for the content-addressed feature cache.

The online path (``cache/store.py``) only evicts inline when a publish
pushes the store over ``cache_max_bytes`` and only size-checks entries
it is about to serve; this tool is the periodic/cron surface that does
the rest:

  * compacts the append-only ``manifest.jsonl`` (put/touch/del op log)
    down to one line per live entry — a busy serving host's manifest
    otherwise grows with every hit;
  * evicts LRU entries down to ``--target-bytes``;
  * ``--verify`` re-hashes every stored file against its recorded
    SHA-256 (not just the size check) and evicts corrupt entries;
  * removes orphaned object directories (crashed writers).

Safe to run against a live cache dir: all mutations go through the same
process-atomic store operations, and concurrent readers degrade evicted
entries to misses.

Usage:
    python tools/cache_gc.py --cache-dir ~/.cache/video_features_tpu/features \\
        [--target-bytes 50000000000] [--verify] [--no-compact]

Prints one JSON report line on stdout. Exit codes:
    0  clean — no corrupt entries found
    1  corrupt/truncated entries were found (and evicted)
    2  usage error (missing/invalid --cache-dir, bad --target-bytes)
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument('--cache-dir', required=True,
                    help='the feature cache directory (cache_dir config key)')
    ap.add_argument('--target-bytes', type=int, default=None,
                    help='evict LRU entries until total stored bytes <= N '
                         '(default: no size pressure)')
    ap.add_argument('--verify', action='store_true',
                    help='re-hash every stored file against its recorded '
                         'SHA-256 (slower; catches silent bit rot the '
                         'size check cannot)')
    ap.add_argument('--no-compact', action='store_true',
                    help='skip the manifest rewrite (report/evict only)')
    ns = ap.parse_args(argv)

    cache_dir = os.path.abspath(os.path.expanduser(ns.cache_dir))
    if not os.path.isdir(cache_dir):
        print(f'error: --cache-dir {ns.cache_dir!r} is not a directory',
              file=sys.stderr)
        return 2
    if ns.target_bytes is not None and ns.target_bytes < 0:
        print('error: --target-bytes must be >= 0', file=sys.stderr)
        return 2

    # a fresh instance, NOT FeatureCache.get: the offline tool must read
    # the manifest as it is on disk, not this process's live view
    from video_features_tpu.cache.store import FeatureCache
    cache = FeatureCache(cache_dir)
    report = cache.gc(target_bytes=ns.target_bytes, verify=ns.verify,
                      compact=not ns.no_compact)
    report['cache_dir'] = cache_dir
    report['verified'] = bool(ns.verify)
    print(json.dumps(report, sort_keys=True))
    return 1 if report['corrupt_evicted'] else 0


if __name__ == '__main__':
    raise SystemExit(main())
