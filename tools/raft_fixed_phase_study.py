#!/usr/bin/env python3
"""Arithmetic floor for RAFT's FIXED phase (VERDICT r4 task 4).

The refinement iteration got a closed floor argument in round 3 (0.88
TFLOP, hand-kernel tie — docs/benchmarks.md "Why a fused GRU kernel…"),
but the fixed phase — encoders + correlation pyramid + convex upsample,
~28% of the mixed-precision fused step — stayed dark. This tool gives
each fixed-phase piece the same treatment at the EXACT shapes the fused
batch-16 step runs (stack 16, 256×344 padded frames → 272 unique fnet
frames, 256 cnet frames, 32×43 /8 feature maps):

  * wall time per fused-step-equivalent (scan-inside-jit, value fetch —
    bench.py methodology),
  * FLOPs from XLA's cost_analysis of the identical sub-graph,
  * achieved TFLOP/s and % of v5e dense-bf16 peak (197 TFLOP/s),

so the phase's remaining headroom is a number per piece, not a guess.

    python tools/raft_fixed_phase_study.py              # real TPU
    BENCH_PLATFORM=cpu python tools/raft_fixed_phase_study.py  # smoke

One JSON line per piece + a totals line; markdown table on stderr.
"""
from __future__ import annotations

import json
import os
import sys
import time
from pathlib import Path

import numpy as np

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

V5E_BF16_PEAK_TFLOPS = 197.0


def measure(jax, device, name, fn, args, ambient, iters):
    """(seconds per call, flops per call) for fn(*args) under ambient
    matmul precision — scan over ``iters`` DISTINCT input batches inside
    one jit (a loop-invariant operand would let XLA hoist the whole pure
    sub-graph out of the loop and divide the time by iters), checksum
    fetch; flops from cost_analysis of the single-call graph."""
    from jax import lax

    # distinct per-iteration inputs: tile + tiny per-slice perturbation
    stacked = tuple(
        np.stack([a + np.float32(i) * np.float32(1e-3)
                  for i in range(iters)]) for a in args)
    dev_args = jax.device_put(stacked, device)

    def one(xs):
        with jax.default_matmul_precision(ambient):
            out = fn(*xs)
        leaves = jax.tree_util.tree_leaves(out)
        return sum(x.sum().astype(np.float32) for x in leaves)

    lowered = jax.jit(one).lower(tuple(a[0] for a in stacked))
    ca = lowered.compile().cost_analysis()
    if isinstance(ca, list):
        ca = ca[0]
    flops = float(ca.get('flops', float('nan')))

    def chained(xs):
        def body(acc, sl):
            return acc + one(sl), None
        acc, _ = lax.scan(body, np.float32(0), xs)
        return acc

    jitted = jax.jit(chained)
    assert np.isfinite(float(jitted(dev_args)))       # compile + warm
    t0 = time.perf_counter()
    assert np.isfinite(float(jitted(dev_args)))
    sec = (time.perf_counter() - t0) / iters
    return name, sec, flops


def main() -> int:
    import jax
    if os.environ.get('BENCH_PLATFORM'):
        jax.config.update('jax_platforms', os.environ['BENCH_PLATFORM'])
    from functools import partial

    from video_features_tpu.models import raft as raft_model
    from video_features_tpu.ops import pallas_corr
    from video_features_tpu.ops.precision import MIXED_AMBIENT
    from video_features_tpu.transplant.torch2jax import transplant
    from video_features_tpu.utils.device import (
        enable_compilation_cache, jax_device,
    )

    platform = jax.devices()[0].platform
    on_accel = platform != 'cpu'
    enable_compilation_cache('~/.cache/video_features_tpu/xla', platform)
    device = jax_device(platform)
    ambient = os.environ.get('BENCH_PRECISION_AMBIENT', MIXED_AMBIENT)
    iters = int(os.environ.get('BENCH_ITERS', 4 if on_accel else 1))

    params = jax.device_put(transplant(raft_model.init_state_dict()),
                            device)
    # fused batch-16 step shapes (stack 16): 16·17 = 272 unique frames,
    # 16·16 = 256 pairs/cnet frames; /8 maps 32×43×256
    B = 16 if on_accel else 1
    S = 16
    h, w = (256, 344) if on_accel else (64, 88)
    h8, w8 = h // 8, w // 8
    n_uniq, n_pairs = B * (S + 1), B * S
    rng = np.random.RandomState(0)
    frames = rng.randint(0, 255, (n_uniq, h, w, 3)).astype(np.float32)
    first = frames[:n_pairs]
    fmap = 0.1 * rng.randn(n_pairs, h8, w8, 256).astype(np.float32)
    fmap2 = 0.1 * rng.randn(n_pairs, h8, w8, 256).astype(np.float32)
    net = rng.randn(n_pairs, h8, w8, 128).astype(np.float32)
    dflow = rng.randn(n_pairs, h8, w8, 2).astype(np.float32)

    def norm_fnet(x):
        return raft_model.basic_encoder(
            params['fnet'], raft_model._normalize_frames(x), 'instance')

    def cnet(x):
        return raft_model.basic_encoder(
            params['cnet'], raft_model._normalize_frames(x), 'batch')

    def pyramid_prep(f1, f2):
        # the PRODUCTION lanes path: transpose-free fused prep (round 5).
        # The superseded two-step path (build_corr_pyramid +
        # prep_pyramid_lanes) measured 106.8 ms at this geometry; keep
        # measuring the shipped one.
        if on_accel:
            return pallas_corr.prep_pyramid_lanes_fused(
                f1, f2, levels=raft_model.CORR_LEVELS)
        return raft_model.build_corr_pyramid(f1, f2)

    def mask_upsample(n, d):
        u = params['update_block']
        t = raft_model.relu(raft_model._conv_b(u['mask']['0'], n, padding=1))
        mask = 0.25 * raft_model._conv_b(u['mask']['2'], t)
        return raft_model.upsample_flow(d, mask)

    pieces = [
        (f'fnet ({n_uniq} frames {h}x{w})', norm_fnet, (frames,)),
        (f'cnet ({n_pairs} frames)', cnet, (first,)),
        ('corr pyramid + lanes prep', pyramid_prep, (fmap, fmap2)),
        ('mask head + convex upsample', mask_upsample, (net, dflow)),
    ]
    rows = []
    for name, fn, args in pieces:
        rows.append(measure(jax, device, name, fn, args, ambient, iters))

    md = ['| piece | ms/step | GFLOPs | TFLOP/s | % v5e bf16 peak |',
          '|---|---|---|---|---|']
    tot_s = tot_f = 0.0
    for name, sec, flops in rows:
        tflops = flops / sec / 1e12
        mfu = tflops / V5E_BF16_PEAK_TFLOPS * 100
        tot_s += sec
        tot_f += flops
        print(json.dumps({
            'piece': name, 'ms_per_step': round(sec * 1e3, 2),
            'gflops': round(flops / 1e9, 2),
            'achieved_tflops': round(tflops, 2),
            'mfu_pct_v5e_bf16': round(mfu, 2), 'ambient': ambient,
        }), flush=True)
        md.append(f'| {name} | {sec * 1e3:.1f} | {flops / 1e9:.1f} | '
                  f'{tflops:.1f} | {mfu:.1f}% |')
    print(json.dumps({
        'piece': 'TOTAL fixed phase', 'ms_per_step': round(tot_s * 1e3, 2),
        'gflops': round(tot_f / 1e9, 2),
        'achieved_tflops': round(tot_f / tot_s / 1e12, 2),
        'mfu_pct_v5e_bf16': round(
            tot_f / tot_s / 1e12 / V5E_BF16_PEAK_TFLOPS * 100, 2),
    }), flush=True)
    md.append(f'| **total** | {tot_s * 1e3:.1f} | {tot_f / 1e9:.1f} | '
              f'{tot_f / tot_s / 1e12:.1f} | '
              f'{tot_f / tot_s / 1e12 / V5E_BF16_PEAK_TFLOPS * 100:.1f}% |')
    print('\n'.join(md), file=sys.stderr)
    return 0


if __name__ == '__main__':
    raise SystemExit(main())
