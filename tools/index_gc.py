#!/usr/bin/env python3
"""Offline maintenance for the sharded feature index.

The online path (``index/``) only tombstones rows inline — cache
eviction and ``del``-record replay mark rows dead in the manifest but
the shard files keep carrying them, and a row whose backing cache
object vanished WITHOUT a manifest record (foreign deletion, partial
restore) stays live. This tool is the periodic/cron surface beside
``cache_gc.py`` / ``aot_gc.py``:

  * ``--orphan-sweep`` drops every row whose cache key the cache no
    longer holds (delete-on-evict coherence for evictions the index
    never heard about);
  * compacts the shards — rewrites them without dead rows and rewrites
    the append-only manifest down to one line per live row.

Safe to run against a live index dir: compaction swaps the manifest
atomically and the store's lock serializes it against a serving
process in the same interpreter; a SEPARATE serving process should be
drained first (same caveat as cache_gc's manifest compaction).

Usage:
    python tools/index_gc.py --cache-dir ~/.cache/video_features_tpu/features \\
        [--index-dir DIR] [--orphan-sweep] [--no-compact]

Prints one JSON report line on stdout. Exit codes:
    0  clean — no orphaned rows found
    1  orphaned rows were found (and dropped)
    2  usage error (missing/invalid --cache-dir)
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument('--cache-dir', required=True,
                    help='the feature cache the index rows point into '
                         '(cache_dir config key)')
    ap.add_argument('--index-dir', default=None,
                    help='index location (default: <cache-dir>/index)')
    ap.add_argument('--orphan-sweep', action='store_true',
                    help='drop rows whose cache key the cache no longer '
                         'holds (evictions the index never heard about)')
    ap.add_argument('--no-compact', action='store_true',
                    help='skip the shard/manifest rewrite (report/sweep '
                         'only)')
    ns = ap.parse_args(argv)

    cache_dir = os.path.abspath(os.path.expanduser(ns.cache_dir))
    if not os.path.isdir(cache_dir):
        print(f'error: --cache-dir {ns.cache_dir!r} is not a directory',
              file=sys.stderr)
        return 2

    from video_features_tpu.index.service import resolve_index_dir
    from video_features_tpu.index.shards import IndexStore
    overrides = {'cache_dir': cache_dir}
    if ns.index_dir:
        overrides['index_dir'] = ns.index_dir
    # fresh instances, NOT .get(): the offline tool must read the
    # manifests as they are on disk, not this process's live view
    store = IndexStore(resolve_index_dir(overrides))
    report = {'index_dir': store.index_dir, 'orphans_dropped': 0}
    if ns.orphan_sweep:
        from video_features_tpu.cache.store import FeatureCache
        cache = FeatureCache(cache_dir)
        report['orphans_dropped'] = store.orphan_sweep(cache.contains)
    if not ns.no_compact:
        report['compact'] = store.compact()
    report.update(store.stats())
    print(json.dumps(report, sort_keys=True))
    return 1 if report['orphans_dropped'] else 0


if __name__ == '__main__':
    raise SystemExit(main())
