# Runtime image for video_features_tpu.
#
# On a Cloud TPU VM the host libtpu is injected by the TPU runtime; for CPU
# (tests/CI) this image is self-contained. The reference ships a conda/cuda
# image (reference Dockerfile); here plain pip + the jax TPU wheel is enough.
FROM python:3.11-slim

RUN apt-get update && apt-get install -y --no-install-recommends \
        ffmpeg build-essential \
    && rm -rf /var/lib/apt/lists/*

WORKDIR /app
COPY pyproject.toml README.md ./
COPY video_features_tpu ./video_features_tpu
COPY native ./native
COPY tools ./tools

# TPU: pip install 'jax[tpu]' -f https://storage.googleapis.com/jax-releases/libtpu_releases.html
RUN pip install --no-cache-dir -e .[torch]

# optional native libav decoder (falls back to cv2 when the build is skipped)
RUN make -C native 2>/dev/null || true

ENTRYPOINT ["python", "-m", "video_features_tpu"]
